"""Elastic-reshard smoke (CPU, < 10 s).

The CI oracle for reshard-on-load (ISSUE 14): a dp4-sharded serial saved
on CPU must (a) reload under a dp2 mesh with every param bitwise-equal
to the serial's assembled logical view, (b) hand each dp2 rank a merged
data cursor whose restored tail equals the uninterrupted dp2 reference
exactly, (c) keep the same-mesh load on the untouched fast path, and
(d) raise the named ``ReshardError`` for a topology the serial cannot
viably land on.

Run directly (``python tools/reshard_smoke.py``) or from tier-1 via
``tests/test_reshard.py::test_reshard_smoke_tool``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_SAMPLES = 96
BATCH_DP4 = 3
STEPS_BEFORE = 2


def main() -> dict:
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu import data
    from paddle_tpu.data.checkpoint import save_data_state
    from paddle_tpu.parallel import multihost as mh
    from paddle_tpu.parallel import reshard
    from paddle_tpu.parallel.mesh import mesh_from_spec

    jax.config.update("jax_platforms", "cpu")
    t0 = time.perf_counter()

    def reader():
        for i in range(N_SAMPLES):
            yield i

    def pipe(n, i, b):
        return (data.from_reader(reader).shuffle(16, seed=3)
                    .shard(n, i).batch(b))

    with tempfile.TemporaryDirectory() as workdir:
        root = os.path.join(workdir, "ckpt")
        mesh4 = mesh_from_spec("dp4")
        rng = np.random.RandomState(0)
        state = {
            "w": jax.device_put(
                rng.normal(size=(8, 4)).astype(np.float32),
                NamedSharding(mesh4, P())),
            "b": jax.device_put(rng.normal(size=(8,)).astype(np.float32),
                                NamedSharding(mesh4, P())),
        }
        mh.save_sharded_serial(state, root, serial=7, meta={"step": 7},
                               mesh=mesh4)
        cur = os.path.join(root, "checkpoint_7")
        # the dp4 fleet's four committed cursors, 2 batches consumed each
        for r in range(4):
            p = pipe(4, r, BATCH_DP4)
            it = iter(p)
            for _ in range(STEPS_BEFORE):
                next(it)
            save_data_state(cur, p.state(), rank=r)
        with open(os.path.join(cur, "meta.json")) as f:
            meta = json.load(f)
        meta.update(process_count=4,
                    data_shards={str(r): [4, r] for r in range(4)})
        with open(os.path.join(cur, "meta.json"), "w") as f:
            json.dump(meta, f)

        # (a) reload under dp2: bitwise vs the assembled logical view
        mesh2 = mesh_from_spec("dp2")
        serial, got_meta, back = mh.load_sharded_latest(root, mesh2, {})
        logical = reshard.assemble_logical(cur)
        bitwise_ok = (serial == 7
                      and got_meta.get("resharded", {}).get("to_mesh")
                      == "dp2"
                      and all(np.array_equal(np.asarray(back[n]),
                                             logical[n])
                              for n in logical)
                      and all(back[n].sharding
                              == NamedSharding(mesh2, P())
                              for n in logical))

        # (b) merged cursors: each dp2 rank's restored tail equals the
        # uninterrupted dp2 reference past the fleet's committed cut
        cut = STEPS_BEFORE * BATCH_DP4 * 4  # samples the dp4 fleet ate
        cursor_ok = True
        for r in range(2):
            cursor = reshard.remap_cursors(cur, meta, "dp2", rank=r,
                                           num_hosts=2)
            p = pipe(2, r, BATCH_DP4 * 2)
            p.restore(cursor)
            tail = [s for b in iter(p) for s in b]
            ref = [s for b in iter(pipe(2, r, BATCH_DP4 * 2)) for s in b]
            cursor_ok = cursor_ok and tail == ref[cut // 2:]

        # (c) the same-topology load never touches reshard code (a clean
        # root: recorded mesh dp4, recorded fleet size == live)
        root_b = os.path.join(workdir, "ckpt_same")
        mh.save_sharded_serial(state, root_b, serial=7, meta={"step": 7},
                               mesh=mesh4)
        calls = []
        orig = reshard.load_resharded
        reshard.load_resharded = lambda *a, **k: calls.append(1) or \
            orig(*a, **k)
        try:
            serial, m2, same = mh.load_sharded_latest(root_b, mesh4, {})
        finally:
            reshard.load_resharded = orig
        fastpath_ok = (not calls and serial == 7
                       and "resharded" not in m2
                       and all(np.array_equal(np.asarray(same[n]),
                                              logical[n])
                               for n in logical))

        # (d) a non-viable topology raises the NAMED error
        try:
            reshard.check_viable(meta, "dp3", num_hosts=3)
            error_ok = False
        except reshard.ReshardError:
            error_ok = True

    report = {
        "ok": bool(bitwise_ok and cursor_ok and fastpath_ok and error_ok),
        "bitwise_ok": bool(bitwise_ok),
        "cursor_ok": bool(cursor_ok),
        "fastpath_ok": bool(fastpath_ok),
        "error_ok": bool(error_ok),
        "cut": cut,
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(report, indent=1))
    return report


if __name__ == "__main__":
    sys.exit(0 if main()["ok"] else 1)
