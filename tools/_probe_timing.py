"""Shared timing harness for the TPU probes (conv_fusion_probe,
train_step_probe).

The timed region ends with an explicit D2H materialization of the final
scalar: over the axon tunnel, ``block_until_ready`` on some result types
has been observed to return early (a pytree 'step' timed at 0.06 ms),
while a host numpy read provably drains the device execution queue — the
same deferred-fetch discipline bench.py uses.
"""

from __future__ import annotations

import json
import time

import numpy as np


def run_timed(kind, fn, args, flops, steps, loss_of=lambda r: r):
    """Print one probe JSON line: compile+settle, time ``steps`` dispatches,
    drain via D2H on loss_of(result); asserts the value is finite."""
    import jax

    float(np.asarray(loss_of(fn(*args))))  # compile + settle
    t0 = time.perf_counter()
    r = None
    for _ in range(steps):
        r = fn(*args)
    last = float(np.asarray(loss_of(r)))
    dt = (time.perf_counter() - t0) / steps
    assert np.isfinite(last), f"non-finite probe output {last}"
    print(json.dumps({"variant": kind,
                      "tflops": round(flops / dt / 1e12, 1),
                      "ms_per_step": round(dt * 1e3, 2),
                      "device": jax.devices()[0].platform}), flush=True)
