"""MNIST models (ref: benchmark/fluid/mnist.py — cnn_model; plus the MLP used
by the book chapter recognize_digits)."""

from __future__ import annotations

from .. import fluid


def mlp(img=None, label=None, hidden_sizes=(128, 64), class_num=10):
    if img is None:
        img = fluid.layers.data(name="img", shape=[784], dtype="float32")
    if label is None:
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    hidden = img
    for size in hidden_sizes:
        hidden = fluid.layers.fc(input=hidden, size=size, act="relu")
    prediction = fluid.layers.fc(input=hidden, size=class_num, act="softmax")
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=prediction, label=label))
    acc = fluid.layers.accuracy(input=prediction, label=label)
    return img, label, prediction, loss, acc


def cnn(img=None, label=None, class_num=10):
    """LeNet-5-style conv net (ref: benchmark/fluid/mnist.py cnn_model)."""
    if img is None:
        img = fluid.layers.data(name="img", shape=[1, 28, 28], dtype="float32")
    if label is None:
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    conv1 = fluid.nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=20, pool_size=2, pool_stride=2,
        act="relu")
    conv2 = fluid.nets.simple_img_conv_pool(
        input=conv1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    prediction = fluid.layers.fc(input=conv2, size=class_num, act="softmax")
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=prediction, label=label))
    acc = fluid.layers.accuracy(input=prediction, label=label)
    return img, label, prediction, loss, acc
