"""DeepFM-style CTR model over sparse categorical features (BASELINE
config #4 "DeepFM-style CTR"; the reference's nearest shape is the
distributed-lookup-table CTR path: sparse ``embedding(is_sparse=True)``
feeding an MLP — ref python/paddle/fluid/layers/nn.py embedding +
transpiler distributed lookup table, distribute_transpiler.py:379-382).

Design: every categorical field is an int64 id into one shared hashed
vocab (the usual CTR trick).  Three towers share the sparse embeddings:

 - first-order: a [V, 1] embedding summed over fields (the linear term)
 - second-order FM: 0.5 * ((sum_f v_f)^2 - sum_f v_f^2) summed over k
 - deep: the concatenated field embeddings through an MLP

All three gradients reach the embedding tables as SelectedRows (is_sparse
=True), so one training step touches only the looked-up rows — the TPU
equivalent of the reference's sparse pserver update.
"""

from __future__ import annotations

from .. import fluid


def build(num_fields=26, vocab_size=10000, embed_dim=8,
          deep_layers=(64, 32), lr=None, is_sparse=True):
    """Returns (feats, label, predict, avg_cost).

    feats: int64 [batch, num_fields] hashed ids; label: float32 [batch, 1].
    Pass lr to attach a (sparse-capable) SGD optimizer.
    """
    feats = fluid.layers.data(name="feats", shape=[num_fields], dtype="int64")
    label = fluid.layers.data(name="label", shape=[1], dtype="float32")

    # first-order term: [B, F, 1] -> sum over fields -> [B, 1]
    w1 = fluid.layers.embedding(
        input=feats, size=[vocab_size, 1], is_sparse=is_sparse,
        param_attr=fluid.ParamAttr(name="fm_w1"))
    first = fluid.layers.reduce_sum(w1, dim=1)

    # shared latent vectors: [B, F, k]
    v = fluid.layers.embedding(
        input=feats, size=[vocab_size, embed_dim], is_sparse=is_sparse,
        param_attr=fluid.ParamAttr(name="fm_v"))

    # FM second-order: 0.5 * sum_k((sum_f v)^2 - sum_f v^2)
    sum_v = fluid.layers.reduce_sum(v, dim=1)              # [B, k]
    sum_v_sq = fluid.layers.square(sum_v)
    v_sq = fluid.layers.square(v)
    sq_sum_v = fluid.layers.reduce_sum(v_sq, dim=1)        # [B, k]
    fm = fluid.layers.scale(
        fluid.layers.reduce_sum(
            fluid.layers.elementwise_sub(sum_v_sq, sq_sum_v),
            dim=1, keep_dim=True),
        scale=0.5)                                          # [B, 1]

    # deep tower over the flattened field embeddings
    deep = fluid.layers.reshape(v, shape=[-1, num_fields * embed_dim])
    for width in deep_layers:
        deep = fluid.layers.fc(input=deep, size=width, act="relu")
    deep = fluid.layers.fc(input=deep, size=1, act=None)

    logit = fluid.layers.elementwise_add(
        fluid.layers.elementwise_add(first, fm), deep)
    predict = fluid.layers.sigmoid(logit)
    cost = fluid.layers.sigmoid_cross_entropy_with_logits(x=logit,
                                                          label=label)
    avg_cost = fluid.layers.mean(cost)

    if lr is not None:
        fluid.optimizer.SGD(learning_rate=lr).minimize(avg_cost)
    return feats, label, predict, avg_cost
