"""SE-ResNeXt (ref: benchmark/fluid/se_resnext.py — ResNeXt bottlenecks with
cardinality-32 grouped convs plus Squeeze-and-Excitation channel gating).

Grouped convs map to ``conv2d(groups=...)`` → one XLA grouped convolution on
the MXU (no per-group loop); the SE gate is two tiny fcs whose broadcasted
channel scale XLA fuses into the surrounding elementwise ops.
"""

from __future__ import annotations

from .. import fluid


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None):
    conv = fluid.layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        act=None, bias_attr=False)
    return fluid.layers.batch_norm(input=conv, act=act)


def squeeze_excitation(input, num_channels, reduction_ratio=16):
    pool = fluid.layers.pool2d(input=input, pool_type="avg",
                               global_pooling=True)
    squeeze = fluid.layers.fc(input=pool,
                              size=num_channels // reduction_ratio,
                              act="relu")
    excitation = fluid.layers.fc(input=squeeze, size=num_channels,
                                 act="sigmoid")
    scale = fluid.layers.reshape(excitation, shape=[-1, num_channels, 1, 1])
    return fluid.layers.elementwise_mul(x=input, y=scale, axis=0)


def _shortcut(input, ch_out, stride):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride)
    return input


def bottleneck_block(input, num_filters, stride, cardinality=32,
                     reduction_ratio=16):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu")
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          groups=cardinality, act="relu")
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1)
    scale = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = _shortcut(input, num_filters * 2, stride)
    return fluid.layers.elementwise_add(x=short, y=scale, act="relu")


_DEPTH_CFG = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}


def se_resnext_imagenet(input, class_dim=1000, depth=50):
    depth_cfg = _DEPTH_CFG[depth]
    num_filters = [128, 256, 512, 1024]
    conv = conv_bn_layer(input, 64, 7, stride=2, act="relu")
    conv = fluid.layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                               pool_padding=1, pool_type="max")
    for block, n_blocks in enumerate(depth_cfg):
        for i in range(n_blocks):
            conv = bottleneck_block(
                conv, num_filters[block], stride=2 if i == 0 and block != 0
                else 1)
    pool = fluid.layers.pool2d(input=conv, pool_type="avg",
                               global_pooling=True)
    drop = fluid.layers.dropout(x=pool, dropout_prob=0.2)
    return fluid.layers.fc(input=drop, size=class_dim, act="softmax")


def build(class_dim=1000, depth=50, image_shape=(3, 224, 224), lr=None):
    img = fluid.layers.data(name="img", shape=list(image_shape),
                            dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    prediction = se_resnext_imagenet(img, class_dim=class_dim, depth=depth)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=prediction, label=label))
    acc = fluid.layers.accuracy(input=prediction, label=label)
    if lr is not None:
        fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9).minimize(loss)
    return img, label, prediction, loss, acc
