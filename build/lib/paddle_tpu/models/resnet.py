"""ResNet for ImageNet/cifar shapes (ref: benchmark/fluid/resnet.py).

Standard He et al. bottleneck architecture expressed in the fluid layer API;
the whole train step compiles to one XLA program whose convs run on the MXU.
"""

from __future__ import annotations

from .. import fluid


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu"):
    conv = fluid.layers.conv2d(
        input=input, num_filters=ch_out, filter_size=filter_size,
        stride=stride, padding=padding, act=None, bias_attr=False)
    return fluid.layers.batch_norm(input=conv, act=act)


def _shortcut(input, ch_out, stride):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None)
    return input


def basicblock(input, ch_out, stride):
    short = _shortcut(input, ch_out, stride)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None)
    return fluid.layers.elementwise_add(x=short, y=conv2, act="relu")


def bottleneck(input, ch_out, stride):
    short = _shortcut(input, ch_out * 4, stride)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None)
    return fluid.layers.elementwise_add(x=short, y=conv3, act="relu")


def _layer_warp(block_func, input, ch_out, count, stride):
    res_out = block_func(input, ch_out, stride)
    for _ in range(1, count):
        res_out = block_func(res_out, ch_out, 1)
    return res_out


_DEPTH_CFG = {
    18: (basicblock, [2, 2, 2, 2]),
    34: (basicblock, [3, 4, 6, 3]),
    50: (bottleneck, [3, 4, 6, 3]),
    101: (bottleneck, [3, 4, 23, 3]),
    152: (bottleneck, [3, 8, 36, 3]),
}


def resnet_imagenet(input, class_dim=1000, depth=50):
    block_func, layers_cfg = _DEPTH_CFG[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2, padding=3)
    pool1 = fluid.layers.pool2d(input=conv1, pool_type="max", pool_size=3,
                                pool_stride=2, pool_padding=1)
    res1 = _layer_warp(block_func, pool1, 64, layers_cfg[0], 1)
    res2 = _layer_warp(block_func, res1, 128, layers_cfg[1], 2)
    res3 = _layer_warp(block_func, res2, 256, layers_cfg[2], 2)
    res4 = _layer_warp(block_func, res3, 512, layers_cfg[3], 2)
    pool2 = fluid.layers.pool2d(input=res4, pool_size=7, pool_type="avg",
                                global_pooling=True)
    out = fluid.layers.fc(input=pool2, size=class_dim, act="softmax")
    return out


def resnet_cifar10(input, class_dim=10, depth=32):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input=input, ch_out=16, filter_size=3, stride=1,
                          padding=1)
    res1 = _layer_warp(basicblock, conv1, 16, n, 1)
    res2 = _layer_warp(basicblock, res1, 32, n, 2)
    res3 = _layer_warp(basicblock, res2, 64, n, 2)
    pool = fluid.layers.pool2d(input=res3, pool_size=8, pool_type="avg",
                               global_pooling=True)
    out = fluid.layers.fc(input=pool, size=class_dim, act="softmax")
    return out


def build(batch_size=None, class_dim=1000, depth=50, image_shape=(3, 224, 224),
          lr=0.01, with_momentum=True):
    """Full train graph: returns (img, label, loss, acc, train_program is the
    default main program)."""
    img = fluid.layers.data(name="img", shape=list(image_shape),
                            dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    if image_shape[-1] <= 32:
        prediction = resnet_cifar10(img, class_dim, depth=32)
    else:
        prediction = resnet_imagenet(img, class_dim, depth=depth)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=prediction, label=label))
    acc = fluid.layers.accuracy(input=prediction, label=label)
    if with_momentum:
        opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9)
    else:
        opt = fluid.optimizer.SGD(learning_rate=lr)
    opt.minimize(loss)
    return img, label, prediction, loss, acc
