"""Model zoo built on the fluid layer API (ref: benchmark/fluid/ models:
mnist, resnet, vgg, se_resnext, stacked_dynamic_lstm, machine_translation)."""

from . import bert, deepfm, mnist, resnet, se_resnext, stacked_lstm, transformer, vgg

__all__ = ["bert", "deepfm", "mnist", "resnet", "se_resnext", "stacked_lstm",
           "transformer", "vgg"]
