"""VGG (ref: benchmark/fluid/vgg.py — VGG-16; depth=19 adds the fourth
conv per late block, matching the VGG-19 the reference's CPU baseline rows
measure, IntelOptimizedPaddle.md:33-35/75-77)."""

from __future__ import annotations

from .. import fluid

# conv counts per block (Simonyan & Zisserman table 1)
_BLOCKS = {16: (2, 2, 3, 3, 3), 19: (2, 2, 4, 4, 4)}


def vgg_bn_drop(input, class_dim=1000, depth=16):
    def conv_block(inp, num_filter, groups, drop):
        # dropout after every conv+bn except the last of the block
        return fluid.nets.img_conv_group(
            input=inp, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * groups, conv_filter_size=3,
            conv_act="relu", conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=[drop] * (groups - 1) + [0.0],
            pool_type="max")

    g = _BLOCKS[depth]
    conv1 = conv_block(input, 64, g[0], 0.3)
    conv2 = conv_block(conv1, 128, g[1], 0.4)
    conv3 = conv_block(conv2, 256, g[2], 0.4)
    conv4 = conv_block(conv3, 512, g[3], 0.4)
    conv5 = conv_block(conv4, 512, g[4], 0.4)

    drop = fluid.layers.dropout(x=conv5, dropout_prob=0.5)
    fc1 = fluid.layers.fc(input=drop, size=512, act=None)
    bn = fluid.layers.batch_norm(input=fc1, act="relu")
    drop2 = fluid.layers.dropout(x=bn, dropout_prob=0.5)
    fc2 = fluid.layers.fc(input=drop2, size=512, act=None)
    prediction = fluid.layers.fc(input=fc2, size=class_dim, act="softmax")
    return prediction


def vgg16_bn_drop(input, class_dim=1000):
    return vgg_bn_drop(input, class_dim, depth=16)


def build(class_dim=10, image_shape=(3, 32, 32), lr=0.01, depth=16):
    img = fluid.layers.data(name="img", shape=list(image_shape),
                            dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    prediction = vgg_bn_drop(img, class_dim, depth=depth)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=prediction, label=label))
    acc = fluid.layers.accuracy(input=prediction, label=label)
    opt = fluid.optimizer.Adam(learning_rate=lr)
    opt.minimize(loss)
    return img, label, prediction, loss, acc
