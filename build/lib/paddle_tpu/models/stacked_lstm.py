"""Stacked dynamic-LSTM sentiment classifier (ref:
benchmark/fluid/stacked_dynamic_lstm.py — embedding → N x (fc + dynamic
LSTM) → sequence max-pool over both towers → softmax).

Variable-length input arrives as a LoDTensor of word ids; the LoD offsets
are static trace metadata (SURVEY.md §5.7), so the scan-based LSTM compiles
to a static XLA while-free program per bucket shape.
"""

from __future__ import annotations

from .. import fluid


def stacked_lstm_net(data, dict_dim, class_dim=2, emb_dim=512,
                     hid_dim=512, stacked_num=3, is_sparse=False):
    emb = fluid.layers.embedding(input=data, size=[dict_dim, emb_dim],
                                 is_sparse=is_sparse)
    fc1 = fluid.layers.fc(input=emb, size=hid_dim)
    lstm1, _ = fluid.layers.dynamic_lstm(input=fc1, size=hid_dim)

    inputs = [fc1, lstm1]
    for _ in range(2, stacked_num + 1):
        fc = fluid.layers.fc(input=inputs, size=hid_dim)
        lstm, _ = fluid.layers.dynamic_lstm(input=fc, size=hid_dim,
                                            is_reverse=False)
        inputs = [fc, lstm]

    fc_last = fluid.layers.sequence_pool(input=inputs[0], pool_type="max")
    lstm_last = fluid.layers.sequence_pool(input=inputs[1], pool_type="max")
    return fluid.layers.fc(input=[fc_last, lstm_last], size=class_dim,
                           act="softmax")


def build(dict_dim=5147, class_dim=2, emb_dim=512, hid_dim=512,
          stacked_num=3, lr=None):
    """data: LoDTensor of int64 word ids [sum_len, 1]; label: [batch, 1]."""
    data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                             lod_level=1)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    prediction = stacked_lstm_net(data, dict_dim, class_dim=class_dim,
                                  emb_dim=emb_dim, hid_dim=hid_dim,
                                  stacked_num=stacked_num)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=prediction, label=label))
    acc = fluid.layers.accuracy(input=prediction, label=label)
    if lr is not None:
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return data, label, prediction, loss, acc
