"""glog-style leveled logging (ref: the reference uses glog VLOG(n)
throughout C++, controlled by GLOG_v / GLOG_logtostderr env vars —
e.g. test_dist_base.py:237 sets them for dist-test subprocesses).

VLOG(n, ...) prints when n <= GLOG_v (default 0 → silent for n >= 1).
Messages go to stderr (glog's default for GLOG_logtostderr=1, which the
reference's Python tooling always sets) with a glog-shaped prefix."""

from __future__ import annotations

import os
import sys
import time


def _verbosity() -> int:
    try:
        return int(os.environ.get("GLOG_v", "0"))
    except ValueError:
        return 0


def vlog_is_on(level: int) -> bool:
    return level <= _verbosity()


def _emit(*msg) -> None:
    t = time.time()
    stamp = time.strftime("%m%d %H:%M:%S", time.localtime(t))
    frac = int((t % 1) * 1e6)
    print(f"I{stamp}.{frac:06d} {os.getpid()} paddle_tpu]",
          *msg, file=sys.stderr)


def VLOG(level: int, *msg) -> None:
    if vlog_is_on(level):
        _emit(*msg)


def LOG(*msg) -> None:
    """Unconditional info log (glog LOG(INFO))."""
    _emit(*msg)
