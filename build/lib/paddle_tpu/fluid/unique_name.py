"""Unique name generator (ref: python/paddle/fluid/unique_name.py)."""

from __future__ import annotations

import contextlib
from collections import defaultdict


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.ids = defaultdict(int)
        self.prefix = prefix

    def __call__(self, key: str) -> str:
        tmp = self.ids[key]
        self.ids[key] += 1
        return self.prefix + "_".join([key, str(tmp)])


generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return generator(key)


def switch(new_generator=None):
    global generator
    old = generator
    generator = new_generator if new_generator is not None else UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        gen = UniqueNameGenerator(new_generator)
    else:
        gen = new_generator
    old = switch(gen)
    try:
        yield
    finally:
        switch(old)
