"""In-graph evaluators (ref: python/paddle/fluid/evaluator.py — Evaluator
:44 keeps accumulator *variables inside the program* so parallel/distributed
runs aggregate on-device; ChunkEvaluator :126, EditDistance :217).

The modern surface is ``fluid.metrics`` (host-side classes, metrics.py);
these program-state evaluators are kept for API parity — chunk_eval /
edit_distance / accuracy ops do the per-batch math, and the evaluator owns
the running counters as persistable vars updated by in-graph ops."""

from __future__ import annotations

import numpy as np

from . import layers
from .framework import Program, Variable, default_main_program, program_guard
from .layer_helper import LayerHelper
from .initializer import Constant

__all__ = ["ChunkEvaluator", "EditDistance", "Accuracy"]


class Evaluator:
    """States are persistable program vars; ``reset`` zeroes them through
    the executor, ``eval`` runs a small fetch program over them (ref
    evaluator.py:44-123)."""

    def __init__(self, name, **kwargs):
        self.states: list = []
        self.metrics: list = []
        self.helper = LayerHelper(name, **kwargs)

    def reset(self, executor, reset_program=None):
        if reset_program is None:
            reset_program = Program()
        with program_guard(main_program=reset_program):
            for var in self.states:
                zeros = layers.fill_constant(
                    shape=list(var.shape), dtype=var.dtype, value=0.0)
                layers.assign(zeros, output=self._clone_into(reset_program,
                                                            var))
        executor.run(reset_program)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError

    def _clone_into(self, program, var):
        block = program.global_block()
        if not block.has_var(var.name):
            nv = block.create_var(name=var.name, shape=var.shape,
                                  dtype=var.dtype, persistable=True)
            return nv
        return block.var(var.name)

    def _create_state(self, suffix, dtype, shape):
        var = self.helper.create_global_variable(
            name="_".join([self.helper.name, suffix]), persistable=True,
            dtype=dtype, shape=list(shape))
        self.helper.set_variable_initializer(var, Constant(0.0))
        self.states.append(var)
        return var


class Accuracy(Evaluator):
    """Running accuracy: correct/total accumulated in-graph."""

    def __init__(self, input, label, k=1, **kwargs):
        super().__init__("accuracy", **kwargs)
        self.total = self._create_state("total", "float32", [1])
        self.correct = self._create_state("correct", "float32", [1])
        acc = layers.accuracy(input=input, label=label, k=k)
        batch = layers.fill_constant_batch_size_like(
            input, shape=[-1, 1], dtype="float32", value=1.0)
        n = layers.reduce_sum(batch)  # = batch size, shape [1]
        correct_b = layers.elementwise_mul(acc, n)
        layers.assign(layers.elementwise_add(self.total, n),
                      output=self.total)
        layers.assign(layers.elementwise_add(self.correct, correct_b),
                      output=self.correct)
        self.metrics.append(acc)

    def eval(self, executor, eval_program=None):
        if eval_program is None:
            eval_program = Program()
        with program_guard(main_program=eval_program):
            total = self._clone_into(eval_program, self.total)
            correct = self._clone_into(eval_program, self.correct)
            out = layers.elementwise_div(
                correct, layers.elementwise_max(
                    total, layers.fill_constant([1], "float32", 1e-6)))
        (v,) = executor.run(eval_program, fetch_list=[out])
        return np.asarray(v)


class ChunkEvaluator(Evaluator):
    """Running chunk F1 (ref evaluator.py:126): accumulates the chunk_eval
    op's per-batch counts into program state and derives P/R/F1."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super().__init__("chunk_eval")
        self.num_infer = self._create_state("num_infer_chunks", "float32", [1])
        self.num_label = self._create_state("num_label_chunks", "float32", [1])
        self.num_correct = self._create_state("num_correct_chunks",
                                              "float32", [1])
        (precision, recall, f1, infer_c, label_c, correct_c) = \
            layers.chunk_eval(input=input, label=label,
                              chunk_scheme=chunk_scheme,
                              num_chunk_types=num_chunk_types,
                              excluded_chunk_types=excluded_chunk_types)
        for state, batch in ((self.num_infer, infer_c),
                             (self.num_label, label_c),
                             (self.num_correct, correct_c)):
            layers.assign(
                layers.elementwise_add(state, layers.cast(batch, "float32")),
                output=state)
        self.metrics.extend([precision, recall, f1])

    def eval(self, executor, eval_program=None):
        if eval_program is None:
            eval_program = Program()
        with program_guard(main_program=eval_program):
            infer = self._clone_into(eval_program, self.num_infer)
            label = self._clone_into(eval_program, self.num_label)
            correct = self._clone_into(eval_program, self.num_correct)
            eps = layers.fill_constant([1], "float32", 1e-6)
            precision = layers.elementwise_div(
                correct, layers.elementwise_max(infer, eps))
            recall = layers.elementwise_div(
                correct, layers.elementwise_max(label, eps))
            two = layers.fill_constant([1], "float32", 2.0)
            f1 = layers.elementwise_div(
                layers.elementwise_mul(
                    two, layers.elementwise_mul(precision, recall)),
                layers.elementwise_max(
                    layers.elementwise_add(precision, recall), eps))
        p, r, f = executor.run(eval_program,
                               fetch_list=[precision, recall, f1])
        return np.asarray(p), np.asarray(r), np.asarray(f)


class EditDistance(Evaluator):
    """Running average edit distance + error-free sequence ratio (ref
    evaluator.py:217)."""

    def __init__(self, input, label, ignored_tokens=None):
        super().__init__("edit_distance")
        self.total_distance = self._create_state("total_distance",
                                                 "float32", [1])
        self.seq_num = self._create_state("seq_num", "float32", [1])
        self.instance_error = self._create_state("instance_error",
                                                 "float32", [1])
        distances, seq_num = layers.edit_distance(
            input=input, label=label, ignored_tokens=ignored_tokens)
        zeros = layers.fill_constant_batch_size_like(
            distances, shape=[-1, 1], dtype="float32", value=0.0)
        errors = layers.cast(distances > zeros, "float32")  # math_op_patch
        layers.assign(layers.elementwise_add(
            self.total_distance, layers.reduce_sum(distances)),
            output=self.total_distance)
        layers.assign(layers.elementwise_add(
            self.seq_num, layers.cast(seq_num, "float32")),
            output=self.seq_num)
        layers.assign(layers.elementwise_add(
            self.instance_error, layers.reduce_sum(errors)),
            output=self.instance_error)
        self.metrics.append(distances)

    def eval(self, executor, eval_program=None):
        if eval_program is None:
            eval_program = Program()
        with program_guard(main_program=eval_program):
            total = self._clone_into(eval_program, self.total_distance)
            n = self._clone_into(eval_program, self.seq_num)
            err = self._clone_into(eval_program, self.instance_error)
            eps = layers.fill_constant([1], "float32", 1e-6)
            avg = layers.elementwise_div(total,
                                         layers.elementwise_max(n, eps))
            ratio = layers.elementwise_div(err,
                                           layers.elementwise_max(n, eps))
        a, r = executor.run(eval_program, fetch_list=[avg, ratio])
        return np.asarray(a), np.asarray(r)
