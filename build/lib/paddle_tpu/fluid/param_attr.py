"""ParamAttr / WeightNormParamAttr (ref: python/paddle/fluid/param_attr.py)."""

from __future__ import annotations

from .initializer import ConstantInitializer, XavierInitializer


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, gradient_clip=None,
                 do_model_average=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average

    @staticmethod
    def _to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, (list, tuple)):
            return [ParamAttr._to_attr(a) for a in arg]
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, bool):
            return ParamAttr._to_attr(None) if arg else False
        if hasattr(arg, "__call__"):  # bare initializer
            return ParamAttr(initializer=arg)
        raise TypeError(f"cannot make ParamAttr from {arg!r}")

    def _set_default_initializer(self, initializer):
        if self.initializer is None:
            self.initializer = initializer

    def _set_default_param_initializer(self):
        self._set_default_initializer(XavierInitializer())

    def _set_default_bias_initializer(self):
        self._set_default_initializer(ConstantInitializer(0.0))

    def _to_kwargs(self, with_initializer=False):
        kwargs = {
            "name": self.name,
            "optimize_attr": {"learning_rate": self.learning_rate},
            "regularizer": self.regularizer,
            "trainable": self.trainable,
            "gradient_clip_attr": self.gradient_clip,
            "do_model_average": self.do_model_average,
        }
        if with_initializer:
            kwargs["initializer"] = self.initializer
        return kwargs


class WeightNormParamAttr(ParamAttr):
    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim
