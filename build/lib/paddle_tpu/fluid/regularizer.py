"""Weight-decay regularizers (ref: python/paddle/fluid/regularizer.py:23,100,178)."""

from __future__ import annotations

from .framework import OpRole

__all__ = ["append_regularization_ops", "L1Decay", "L2Decay",
           "L1DecayRegularizer", "L2DecayRegularizer"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        decay = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(type="scale", inputs={"X": [param]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self._regularization_coeff,
                               OpRole.KEY: OpRole.Backward})
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        sign = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(type="sign", inputs={"X": [param]},
                        outputs={"Out": [sign]},
                        attrs={OpRole.KEY: OpRole.Backward})
        decay = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(type="scale", inputs={"X": [sign]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self._regularization_coeff,
                               OpRole.KEY: OpRole.Backward})
        return decay


def _create_regularization_of_grad(param, grad, regularization=None):
    regularizer = getattr(param, "regularizer", None) or regularization
    if regularizer is None:
        return grad
    block = grad.block
    decay = regularizer(param, grad, block)
    new_grad = block.create_var(name=grad.name + "_regularized",
                                dtype=grad.dtype, shape=grad.shape)
    block.append_op(type="sum", inputs={"X": [grad, decay]},
                    outputs={"Out": [new_grad]},
                    attrs={OpRole.KEY: OpRole.Backward})
    return new_grad


def append_regularization_ops(parameters_and_grads, regularization=None):
    params_and_grads = []
    for param, grad in parameters_and_grads:
        if grad is None:
            params_and_grads.append((param, grad))
            continue
        new_grad = _create_regularization_of_grad(param, grad, regularization)
        params_and_grads.append((param, new_grad))
    return params_and_grads


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
