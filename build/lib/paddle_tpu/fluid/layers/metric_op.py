"""Metric layers (ref: python/paddle/fluid/layers/metric_op.py)."""

from __future__ import annotations

from ..initializer import ConstantInitializer
from ..layer_helper import LayerHelper

__all__ = ["accuracy", "auc", "chunk_eval"]


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    from .nn import topk

    topk_out, topk_indices = topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference(dtype="float32",
                                                        stop_gradient=True)
    acc_out.shape = (1,)
    if correct is None:
        correct = helper.create_variable_for_type_inference("int32",
                                                            stop_gradient=True)
    if total is None:
        total = helper.create_variable_for_type_inference("int32",
                                                          stop_gradient=True)
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices],
                "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct],
                 "Total": [total]})
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1):
    helper = LayerHelper("auc")
    stat_pos = helper.create_global_variable(
        persistable=True, dtype="float32", shape=[num_thresholds + 1])
    helper.set_variable_initializer(stat_pos, ConstantInitializer(0.0))
    stat_neg = helper.create_global_variable(
        persistable=True, dtype="float32", shape=[num_thresholds + 1])
    helper.set_variable_initializer(stat_neg, ConstantInitializer(0.0))
    auc_out = helper.create_variable_for_type_inference("float32",
                                                        stop_gradient=True)
    auc_out.shape = (1,)
    helper.append_op(
        type="auc",
        inputs={"Predict": [input], "Label": [label], "StatPos": [stat_pos],
                "StatNeg": [stat_neg]},
        outputs={"AUC": [auc_out], "StatPosOut": [stat_pos],
                 "StatNegOut": [stat_neg]},
        attrs={"curve": curve, "num_thresholds": num_thresholds})
    return auc_out, [stat_pos, stat_neg]


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    """ref: layers/nn.py chunk_eval — per-batch chunk P/R/F1 + raw counts
    for a running evaluator."""
    helper = LayerHelper("chunk_eval", **locals())
    precision = helper.create_variable_for_type_inference("float32")
    recall = helper.create_variable_for_type_inference("float32")
    f1 = helper.create_variable_for_type_inference("float32")
    num_infer = helper.create_variable_for_type_inference("int64")
    num_label = helper.create_variable_for_type_inference("int64")
    num_correct = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="chunk_eval",
        inputs={"Inference": [input], "Label": [label]},
        outputs={"Precision": [precision], "Recall": [recall],
                 "F1-Score": [f1], "NumInferChunks": [num_infer],
                 "NumLabelChunks": [num_label],
                 "NumCorrectChunks": [num_correct]},
        attrs={"chunk_scheme": chunk_scheme,
               "num_chunk_types": num_chunk_types,
               "excluded_chunk_types": excluded_chunk_types or []})
    return precision, recall, f1, num_infer, num_label, num_correct
