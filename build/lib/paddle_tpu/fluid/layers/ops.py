"""Auto-generated elementwise/activation layer wrappers
(ref: python/paddle/fluid/layers/ops.py:47 — generated from OpProtos via
layer_function_generator.py; here generated from the op registry)."""

from __future__ import annotations

from ..layer_helper import LayerHelper

_UNARY_OPS = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "softshrink",
    "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin", "round",
    "reciprocal", "log", "square", "softplus", "softsign", "relu",
    "soft_relu", "gelu", "log_softmax",
]

_UNARY_ATTR_OPS = {
    "relu6": {"threshold": 6.0},
    "leaky_relu": {"alpha": 0.02},
    "elu": {"alpha": 1.0},
    "pow": {"factor": 1.0},
    "stanh": {"scale_a": 0.67, "scale_b": 1.7159},
    "hard_sigmoid": {"slope": 0.2, "offset": 0.5},
    "hard_shrink": {"threshold": 0.5},
    "thresholded_relu": {"threshold": 1.0},
    "brelu": {"t_min": 0.0, "t_max": 24.0},
    "swish": {"beta": 1.0},
}

__all__ = list(_UNARY_OPS) + list(_UNARY_ATTR_OPS) + [
    "uniform_random", "cumsum",
    "logical_and", "logical_or", "logical_xor", "logical_not",
]


def _make_logical(op_type):
    binary = op_type != "logical_not"

    def layer(x, y=None, out=None, name=None):
        helper = LayerHelper(op_type, name=name)
        if out is None:
            out = helper.create_variable_for_type_inference(dtype="bool")
            # static shape = the broadcast of both operands
            shp = x.shape
            if binary and y is not None and y.shape is not None:
                if shp is None or len(y.shape) > len(shp):
                    shp = y.shape
            out.shape = shp
        inputs = {"X": [x]}
        if binary:
            inputs["Y"] = [y]
        helper.append_op(type=op_type, inputs=inputs,
                         outputs={"Out": [out]})
        return out

    layer.__name__ = op_type
    return layer


logical_and = _make_logical("logical_and")
logical_or = _make_logical("logical_or")
logical_xor = _make_logical("logical_xor")
logical_not = _make_logical("logical_not")


def _make_unary(op_type, default_attrs=None):
    def layer(x, name=None, **kwargs):
        helper = LayerHelper(op_type, name=name)
        attrs = dict(default_attrs or {})
        for k in attrs:
            if k in kwargs:
                attrs[k] = kwargs[k]
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        out.shape = x.shape
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]}, attrs=attrs)
        return out

    layer.__name__ = op_type
    layer.__doc__ = f"{op_type} activation (see ops/activation_ops.py)"
    return layer


for _name in _UNARY_OPS:
    globals()[_name] = _make_unary(_name)
for _name, _attrs in _UNARY_ATTR_OPS.items():
    globals()[_name] = _make_unary(_name, _attrs)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    out.shape = tuple(shape)
    helper.append_op(type="uniform_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "min": min, "max": max, "seed": seed})
    return out


def cumsum(x, axis=-1, exclusive=False, reverse=False):
    helper = LayerHelper("cumsum")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    helper.append_op(type="cumsum", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis, "exclusive": exclusive,
                            "reverse": reverse})
    return out
