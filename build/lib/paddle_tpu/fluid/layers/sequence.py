"""Sequence & recurrent layers (ref: python/paddle/fluid/layers/nn.py —
dynamic_lstm/dynamic_gru/sequence_* entries; SURVEY.md §2.4 sequence family).
"""

from __future__ import annotations

from ..layer_helper import LayerHelper
from ..framework import Variable

__all__ = [
    "dynamic_lstm", "dynamic_lstmp", "dynamic_gru", "gru_unit", "lstm_unit",
    "sequence_conv", "sequence_pool", "sequence_softmax", "sequence_concat",
    "sequence_first_step", "sequence_last_step", "sequence_expand",
    "sequence_expand_as", "sequence_pad", "sequence_unpad", "sequence_slice",
    "sequence_reshape", "sequence_enumerate", "sequence_mask",
    "sequence_reverse", "row_conv", "beam_search", "beam_search_decode",
]


def _out(helper, dtype, shape=None):
    v = helper.create_variable_for_type_inference(dtype=dtype)
    if shape is not None:
        v.shape = tuple(shape)
    return v


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """ref: layers/nn.py dynamic_lstm.  ``input`` is the 4*hidden projection
    (apply fc first); ``size`` is 4*hidden."""
    helper = LayerHelper("dynamic_lstm", **locals())
    d = size // 4
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[d, 4 * d], dtype=dtype)
    bias_size = [1, 7 * d] if use_peepholes else [1, 4 * d]
    bias = helper.create_parameter(attr=helper.bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    hidden = _out(helper, dtype, (input.shape[0], d))
    cell = _out(helper, dtype, (input.shape[0], d))
    batch_gate = _out(helper, dtype)
    batch_cell_pre_act = _out(helper, dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(
        type="dynamic_lstm", inputs=inputs,
        outputs={"Hidden": [hidden], "Cell": [cell],
                 "BatchGate": [batch_gate],
                 "BatchCellPreAct": [batch_cell_pre_act]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation})
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    """ref: layers/nn.py dynamic_lstmp (LSTM with recurrent projection)."""
    helper = LayerHelper("dynamic_lstmp", **locals())
    d = size // 4
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[proj_size, 4 * d], dtype=dtype)
    proj_weight = helper.create_parameter(attr=helper.param_attr,
                                          shape=[d, proj_size], dtype=dtype)
    bias_size = [1, 7 * d] if use_peepholes else [1, 4 * d]
    bias = helper.create_parameter(attr=helper.bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    projection = _out(helper, dtype, (input.shape[0], proj_size))
    cell = _out(helper, dtype, (input.shape[0], d))
    helper.append_op(
        type="dynamic_lstmp",
        inputs={"Input": [input], "Weight": [weight],
                "ProjWeight": [proj_weight], "Bias": [bias]},
        outputs={"Projection": [projection], "Cell": [cell]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation})
    return projection, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False):
    """ref: layers/nn.py dynamic_gru.  ``input`` is the 3*size projection."""
    helper = LayerHelper("dynamic_gru", **locals())
    dtype = input.dtype
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(attr=helper.bias_attr,
                                   shape=[1, 3 * size], dtype=dtype,
                                   is_bias=True)
    hidden = _out(helper, dtype, (input.shape[0], size))
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(
        type="dynamic_gru", inputs=inputs, outputs={"Hidden": [hidden]},
        attrs={"is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "activation": candidate_activation,
               "origin_mode": origin_mode})
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    """ref: layers/nn.py gru_unit — one GRU step; returns
    (hidden, reset_hidden_prev, gate)."""
    helper = LayerHelper("gru_unit", **locals())
    dtype = input.dtype
    d = size // 3
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[d, 3 * d], dtype=dtype)
    bias = helper.create_parameter(attr=helper.bias_attr, shape=[1, 3 * d],
                                   dtype=dtype, is_bias=True)
    act_enum = {"identity": 0, "sigmoid": 1, "tanh": 2, "relu": 3}
    gate = _out(helper, dtype)
    reset_hidden_prev = _out(helper, dtype)
    updated_hidden = _out(helper, dtype, (input.shape[0], d))
    helper.append_op(
        type="gru_unit",
        inputs={"Input": [input], "HiddenPrev": [hidden],
                "Weight": [weight], "Bias": [bias]},
        outputs={"Gate": [gate], "ResetHiddenPrev": [reset_hidden_prev],
                 "Hidden": [updated_hidden]},
        attrs={"activation": act_enum[activation],
               "gate_activation": act_enum[gate_activation]})
    return updated_hidden, reset_hidden_prev, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """ref: layers/nn.py lstm_unit — fc([x_t, h_prev]) -> lstm_unit op;
    returns (hidden, cell)."""
    from .nn import fc
    from .tensor import concat

    helper = LayerHelper("lstm_unit", **locals())
    size = cell_t_prev.shape[-1]
    cat = concat([x_t, hidden_t_prev], axis=1)
    fc_out = fc(cat, size=4 * size, param_attr=param_attr,
                bias_attr=bias_attr)
    dtype = x_t.dtype
    c = _out(helper, dtype, cell_t_prev.shape)
    h = _out(helper, dtype, hidden_t_prev.shape)
    helper.append_op(
        type="lstm_unit", inputs={"X": [fc_out], "C_prev": [cell_t_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": float(forget_bias)})
    return h, c


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None,
                  name=None):
    """ref: layers/nn.py sequence_conv."""
    helper = LayerHelper("sequence_conv", **locals())
    dtype = helper.input_dtype()
    filter_shape = [filter_size * input.shape[1], num_filters]
    filter_param = helper.create_parameter(attr=helper.param_attr,
                                           shape=filter_shape, dtype=dtype)
    pre_bias = _out(helper, dtype, (input.shape[0], num_filters))
    helper.append_op(
        type="sequence_conv",
        inputs={"X": [input], "Filter": [filter_param]},
        outputs={"Out": [pre_bias]},
        attrs={"contextStride": filter_stride,
               "contextStart": -int(filter_size // 2),
               "contextLength": filter_size})
    pre_act = helper.append_bias_op(pre_bias)
    return helper.append_activation(pre_act)


def sequence_pool(input, pool_type):
    """ref: layers/nn.py sequence_pool."""
    helper = LayerHelper("sequence_pool", **locals())
    dtype = helper.input_dtype()
    pool_out = _out(helper, dtype, (-1,) + tuple(input.shape[1:]))
    max_index = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="sequence_pool", inputs={"X": [input]},
        outputs={"Out": [pool_out], "MaxIndex": [max_index]},
        attrs={"pooltype": pool_type.upper()})
    if pool_type == "max":
        max_index.stop_gradient = True
    return pool_out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", **locals())
    out = _out(helper, input.dtype, input.shape)
    helper.append_op(type="sequence_softmax", inputs={"X": [input]},
                     outputs={"Out": [out]})
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", **locals())
    inputs = input if isinstance(input, (list, tuple)) else [input]
    out = _out(helper, inputs[0].dtype)
    helper.append_op(type="sequence_concat", inputs={"X": list(inputs)},
                     outputs={"Out": [out]})
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", **locals())
    # rows are dynamic (expansion counts come from y's LoD) but trailing
    # dims survive — downstream fc/shape math needs them
    out = _out(helper, x.dtype,
               shape=((-1,) + tuple(x.shape[1:])) if x.shape else None)
    helper.append_op(type="sequence_expand", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"ref_level": ref_level})
    return out


def sequence_expand_as(x, y, name=None):
    helper = LayerHelper("sequence_expand_as", **locals())
    out = _out(helper, x.dtype)
    helper.append_op(type="sequence_expand_as", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def sequence_pad(x, pad_value, maxlen=None, name=None):
    helper = LayerHelper("sequence_pad", **locals())
    out = _out(helper, x.dtype)
    length = helper.create_variable_for_type_inference(dtype="int64")
    length.stop_gradient = True
    helper.append_op(
        type="sequence_pad", inputs={"X": [x], "PadValue": [pad_value]},
        outputs={"Out": [out], "Length": [length]},
        attrs={"padded_length": -1 if maxlen is None else maxlen})
    return out, length


def sequence_unpad(x, length, name=None):
    helper = LayerHelper("sequence_unpad", **locals())
    out = _out(helper, x.dtype)
    helper.append_op(type="sequence_unpad",
                     inputs={"X": [x], "Length": [length]},
                     outputs={"Out": [out]})
    return out


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", **locals())
    out = _out(helper, input.dtype)
    offset.stop_gradient = True
    length.stop_gradient = True
    helper.append_op(type="sequence_slice",
                     inputs={"X": [input], "Offset": [offset],
                             "Length": [length]},
                     outputs={"Out": [out]})
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape", **locals())
    out = _out(helper, input.dtype, (-1, new_dim))
    helper.append_op(type="sequence_reshape", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"new_dim": new_dim})
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    helper = LayerHelper("sequence_enumerate", **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    out.stop_gradient = True
    helper.append_op(type="sequence_enumerate", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"win_size": win_size, "pad_value": pad_value})
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", **locals())
    out = helper.create_variable_for_type_inference(dtype=dtype)
    out.stop_gradient = True
    helper.append_op(type="sequence_mask", inputs={"X": [x]},
                     outputs={"Y": [out]},
                     attrs={"maxlen": -1 if maxlen is None else maxlen,
                            "out_dtype": dtype})
    return out


def sequence_reverse(x, name=None):
    helper = LayerHelper("sequence_reverse", **locals())
    out = _out(helper, x.dtype, x.shape)
    helper.append_op(type="sequence_reverse", inputs={"X": [x]},
                     outputs={"Y": [out]})
    return out


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, name=None):
    """ref: layers/nn.py:2780 — one beam-search step (executor eager tier;
    fixed-width beams, see ops/array_ops.py beam_search)."""
    helper = LayerHelper("beam_search", **locals())
    selected_ids = helper.create_variable_for_type_inference(dtype="int64")
    selected_scores = helper.create_variable_for_type_inference(
        dtype=scores.dtype)
    inputs = {"pre_ids": [pre_ids], "scores": [scores]}
    if pre_scores is not None:
        inputs["pre_scores"] = [pre_scores]
    if ids is not None:
        inputs["ids"] = [ids]
    helper.append_op(
        type="beam_search", inputs=inputs,
        outputs={"selected_ids": [selected_ids],
                 "selected_scores": [selected_scores]},
        attrs={"level": level, "beam_size": beam_size, "end_id": end_id})
    return selected_ids, selected_scores


def beam_search_decode(ids, scores, beam_size=None, end_id=None, name=None):
    """ref: layers/nn.py:2892 — backtrack hypotheses from step arrays."""
    helper = LayerHelper("beam_search_decode", **locals())
    sentence_ids = helper.create_variable_for_type_inference(dtype="int64")
    sentence_scores = helper.create_variable_for_type_inference(
        dtype="float32")
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "Scores": [scores]},
        outputs={"SentenceIds": [sentence_ids],
                 "SentenceScores": [sentence_scores]},
        attrs={"beam_size": beam_size or 0, "end_id": -1 if end_id is None
               else end_id})
    return sentence_ids, sentence_scores


def row_conv(input, future_context_size, param_attr=None, act=None):
    """ref: layers/nn.py row_conv (lookahead convolution)."""
    helper = LayerHelper("row_conv", **locals())
    dtype = helper.input_dtype()
    filter_shape = [future_context_size + 1, input.shape[1]]
    filter_param = helper.create_parameter(attr=helper.param_attr,
                                           shape=filter_shape, dtype=dtype)
    out = _out(helper, dtype, input.shape)
    helper.append_op(type="row_conv",
                     inputs={"X": [input], "Filter": [filter_param]},
                     outputs={"Out": [out]})
    return helper.append_activation(out)
