"""Tensor-construction layers (ref: python/paddle/fluid/layers/tensor.py)."""

from __future__ import annotations

import numpy as np

from .. import core, unique_name
from ..framework import Variable
from ..initializer import ConstantInitializer
from ..layer_helper import LayerHelper

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "cast",
    "concat", "sums", "assign", "fill_constant",
    "fill_constant_batch_size_like", "ones", "zeros", "reverse",
    "argmin", "argmax", "argsort", "has_inf", "has_nan", "isfinite",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    helper = LayerHelper("create_parameter", name=name)
    from ..param_attr import ParamAttr

    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(name=helper.name if name is None else name,
                                        dtype=dtype, shape=shape,
                                        persistable=persistable)
    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast")
    dtype = core.convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    out.shape = x.shape
    helper.append_op(type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"in_dtype": x.dtype, "out_dtype": dtype})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", input=input, name=name)
    out = helper.create_variable_for_type_inference(dtype=helper.input_dtype())
    xs = helper.multiple_input()
    if all(v.shape is not None for v in xs):
        shape = list(xs[0].shape)
        ax = axis % len(shape)
        tot = 0
        for v in xs:
            d = v.shape[ax]
            tot = -1 if (d in (-1, None) or tot == -1) else tot + d
        shape[ax] = tot
        out.shape = tuple(shape)
    helper.append_op(type="concat", inputs={"X": xs},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum", input=input)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=helper.input_dtype())
        out.shape = helper.multiple_input()[0].shape
    helper.append_op(type="sum", inputs={"X": helper.multiple_input()},
                     outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(dtype=input.dtype)
            output.shape = input.shape
        helper.append_op(type="assign", inputs={"X": [input]},
                         outputs={"Out": [output]})
    elif isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_variable_for_type_inference(
                dtype=core.convert_dtype(input.dtype))
            output.shape = tuple(input.shape)
        helper.append_op(
            type="assign_value", outputs={"Out": [output]},
            attrs={"shape": list(input.shape),
                   "dtype": core.convert_dtype(input.dtype),
                   "fp32_values": [float(v) for v in input.flat]})
    else:
        raise TypeError("assign expects Variable or numpy array")
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype=core.convert_dtype(dtype))
    out.shape = tuple(shape)
    out.stop_gradient = True
    helper.append_op(type="fill_constant", outputs={"Out": [out]},
                     attrs={"shape": list(shape),
                            "dtype": core.convert_dtype(dtype),
                            "value": float(value),
                            "force_cpu": bool(force_cpu)})
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(
        dtype=core.convert_dtype(dtype))
    s = list(shape)
    s[output_dim_idx] = -1
    out.shape = tuple(s)
    out.stop_gradient = True
    helper.append_op(type="fill_constant_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape),
                            "dtype": core.convert_dtype(dtype),
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=0.0)


def reverse(x, axis):
    helper = LayerHelper("reverse")
    if isinstance(axis, int):
        axis = [axis]
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    helper.append_op(type="reverse", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def _arg_op(op_type, x, axis):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    if x.shape is not None:
        s = list(x.shape)
        del s[axis % len(s)]
        out.shape = tuple(s)
    helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def argmin(x, axis=0):
    return _arg_op("arg_min", x, axis)


def argmax(x, axis=0):
    return _arg_op("arg_max", x, axis)


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    ids = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    out.shape = input.shape
    ids.shape = input.shape
    helper.append_op(type="argsort", inputs={"X": [input]},
                     outputs={"Out": [out], "Indices": [ids]},
                     attrs={"axis": axis})
    return out, ids


def _bool_reduce(op_type, x):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(dtype="bool",
                                                    stop_gradient=True)
    out.shape = (1,)
    helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def isfinite(x):
    """True iff ALL elements are finite (ref: isfinite_op.cc)."""
    return _bool_reduce("isfinite", x)


def has_inf(x):
    """True iff ANY element is +/-Inf."""
    return _bool_reduce("has_inf", x)


def has_nan(x):
    """True iff ANY element is NaN."""
    return _bool_reduce("has_nan", x)
