"""Operator overloading on Variable (ref: layers/math_op_patch.py)."""

from __future__ import annotations

from ..framework import Variable
from ..layer_helper import LayerHelper


def _create_op(op_type, x, y, axis=-1, out_dtype=None):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(out_dtype or x.dtype)
    out.shape = x.shape
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def _scalar_op(x, scale, bias):
    helper = LayerHelper("scale")
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": True})
    return out


def _to_var(x, ref):
    """Promote a python scalar to a filled tensor shaped like `ref`."""
    from . import tensor as _tensor

    if isinstance(x, Variable):
        return x
    return _tensor.fill_constant(shape=[1], dtype=ref.dtype, value=float(x))


def _binary(op_type, reverse=False):
    def impl(self, other):
        if isinstance(other, (int, float)):
            if op_type == "elementwise_add":
                return _scalar_op(self, 1.0, other)
            if op_type == "elementwise_sub":
                if reverse:
                    return _scalar_op(self, -1.0, other)
                return _scalar_op(self, 1.0, -other)
            if op_type == "elementwise_mul":
                return _scalar_op(self, other, 0.0)
            if op_type == "elementwise_div" and not reverse:
                return _scalar_op(self, 1.0 / other, 0.0)
            other = _to_var(other, self)
        x, y = (other, self) if reverse else (self, other)
        if not isinstance(x, Variable):
            x = _to_var(x, self)
        return _create_op(op_type, x, y)

    return impl


def _compare(op_type):
    def impl(self, other):
        other = _to_var(other, self)
        return _create_op(op_type, self, other, out_dtype="bool")

    return impl


def monkey_patch_variable():
    Variable.__add__ = _binary("elementwise_add")
    Variable.__radd__ = _binary("elementwise_add")
    Variable.__sub__ = _binary("elementwise_sub")
    Variable.__rsub__ = _binary("elementwise_sub", reverse=True)
    Variable.__mul__ = _binary("elementwise_mul")
    Variable.__rmul__ = _binary("elementwise_mul")
    Variable.__truediv__ = _binary("elementwise_div")
    Variable.__rtruediv__ = _binary("elementwise_div", reverse=True)
    Variable.__div__ = Variable.__truediv__
    Variable.__pow__ = _binary("elementwise_pow")
    Variable.__rpow__ = _binary("elementwise_pow", reverse=True)
    Variable.__lt__ = _compare("less_than")
    Variable.__le__ = _compare("less_equal")
    Variable.__gt__ = _compare("greater_than")
    Variable.__ge__ = _compare("greater_equal")
    Variable.__neg__ = lambda self: _scalar_op(self, -1.0, 0.0)
