"""Control-flow layers (ref: python/paddle/fluid/layers/control_flow.py:30 —
While, Switch, IfElse, DynamicRNN, StaticRNN, lod_rank_table, arrays).

TPU design: a ``while`` op's sub-block is unrolled into the XLA trace with a
concrete (counter/lod-rooted) condition — see fluid/control_flow_exec.py.
DynamicRNN mirrors the reference's construction exactly (rank table +
tensor arrays + shrinking memories); StaticRNN uses the same while loop over
a statically-known step count with stack/unstack arrays.  IfElse lowers to
split/merge-by-mask, which runs in the executor's eager tier.
"""

from __future__ import annotations

import contextlib

from .. import unique_name
from ..framework import Variable
from ..layer_helper import LayerHelper
from .tensor import fill_constant

__all__ = [
    "While", "Switch", "IfElse", "DynamicRNN", "StaticRNN",
    "increment", "is_empty", "less_than", "equal", "array_length",
    "array_read", "array_write", "create_array", "lod_rank_table",
    "max_sequence_len", "lod_tensor_to_array", "array_to_lod_tensor",
    "shrink_memory", "reorder_lod_tensor_by_rank",
]


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        out.shape = x.shape
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def less_than(x, y, force_cpu=None, cond=None):
    helper = LayerHelper("less_than")
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool",
                                                         stop_gradient=True)
        cond.shape = x.shape
    helper.append_op(type="less_than", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def equal(x, y, cond=None):
    helper = LayerHelper("equal")
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool",
                                                         stop_gradient=True)
        cond.shape = x.shape
    helper.append_op(type="equal", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool",
                                                         stop_gradient=True)
    helper.append_op(type="is_empty", inputs={"X": [x]},
                     outputs={"Out": [cond]})
    return cond


# ---------------------------------------------------------------------------
# tensor arrays
# ---------------------------------------------------------------------------


def create_array(dtype):
    helper = LayerHelper("array")
    from .. import core

    return helper.main_program.current_block().create_var(
        name=unique_name.generate("array"), dtype=dtype,
        type=core.VarType.LOD_TENSOR_ARRAY)


def array_write(x, i, array=None):
    """ref: write_to_array."""
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    if getattr(array, "shape", None) is None and x.shape is not None:
        array.shape = tuple(x.shape)
    helper.append_op(type="write_to_array",
                     inputs={"X": [x], "I": [i]},
                     outputs={"Out": [array]})
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(dtype=array.dtype)
    out.shape = getattr(array, "shape", None)
    helper.append_op(type="read_from_array",
                     inputs={"X": [array], "I": [i]},
                     outputs={"Out": [out]})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    helper.append_op(type="lod_array_length", inputs={"X": [array]},
                     outputs={"Out": [out]})
    return out


def lod_rank_table(x, level=0):
    """ref: lod_rank_table_op.cc."""
    helper = LayerHelper("lod_rank_table")
    from .. import core

    table = helper.main_program.current_block().create_var(
        name=unique_name.generate("lod_rank_table"),
        type=core.VarType.LOD_RANK_TABLE)
    table.stop_gradient = True
    helper.append_op(type="lod_rank_table", inputs={"X": [x]},
                     outputs={"Out": [table]}, attrs={"level": level})
    return table


def max_sequence_len(rank_table):
    helper = LayerHelper("max_seqence_length")
    out = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    helper.append_op(type="max_sequence_len",
                     inputs={"RankTable": [rank_table]},
                     outputs={"Out": [out]})
    return out


def lod_tensor_to_array(x, table):
    helper = LayerHelper("lod_tensor_to_array")
    from .. import core

    array = helper.main_program.current_block().create_var(
        name=unique_name.generate("lod_tensor_to_array"), dtype=x.dtype,
        type=core.VarType.LOD_TENSOR_ARRAY)
    helper.append_op(type="lod_tensor_to_array",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [array]})
    return array


def array_to_lod_tensor(x, table):
    helper = LayerHelper("array_to_lod_tensor")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = getattr(x, "shape", None)
    helper.append_op(type="array_to_lod_tensor",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [out]})
    return out


def shrink_memory(x, i, table):
    helper = LayerHelper("shrink_memory")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    helper.append_op(type="shrink_rnn_memory",
                     inputs={"X": [x], "I": [i], "RankTable": [table]},
                     outputs={"Out": [out]})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    helper.append_op(type="reorder_lod_tensor_by_rank",
                     inputs={"X": [x], "RankTable": [rank_table]},
                     outputs={"Out": [out]})
    return out


# ---------------------------------------------------------------------------
# While
# ---------------------------------------------------------------------------


class BlockGuard:
    def __init__(self, main_program):
        self.main_program = main_program

    def __enter__(self):
        self.main_program._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.main_program._rollback()
        return exc_type is None


class WhileGuard(BlockGuard):
    def __init__(self, while_op):
        super().__init__(while_op.helper.main_program)
        self.while_op = while_op

    def __enter__(self):
        self.while_op.status = While.IN_WHILE_BLOCK
        return super().__enter__()

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            # still roll back out of the sub-block so a caught exception
            # doesn't leave later layers appending into the dead body
            super().__exit__(exc_type, exc_val, exc_tb)
            return False
        self.while_op.status = While.AFTER_WHILE_BLOCK
        self.while_op._complete()
        return super().__exit__(exc_type, exc_val, exc_tb)


class While:
    """ref: control_flow.py:655.  The condition must be concrete at trace
    time (counter/lod-rooted) — see fluid/control_flow_exec.py."""

    BEFORE_WHILE_BLOCK = 0
    IN_WHILE_BLOCK = 1
    AFTER_WHILE_BLOCK = 2

    def __init__(self, cond, is_test=False, name=None):
        self.helper = LayerHelper("while", name=name)
        self.status = While.BEFORE_WHILE_BLOCK
        if not isinstance(cond, Variable):
            raise TypeError("condition should be a variable")
        self.cond_var = cond
        self.is_test = is_test

    def block(self):
        return WhileGuard(self)

    def _complete(self):
        main_program = self.helper.main_program
        while_block = main_program.current_block()
        parent_block = main_program.block(while_block.parent_idx)

        # X: names read in the body but defined outside it;
        # Out: names written in the body that exist outside it
        written = set()
        x_names, out_names = [], []
        for op in while_block.ops:
            for n in op.input_arg_names:
                if not n or n in written or n in x_names:
                    continue
                if parent_block._has_var_recursive(n):
                    x_names.append(n)
            for n in op.output_arg_names:
                if not n:
                    continue
                written.add(n)
                if parent_block._has_var_recursive(n) and n not in out_names:
                    out_names.append(n)
        if self.cond_var.name not in x_names:
            x_names.append(self.cond_var.name)

        from .. import core

        step_scope = parent_block.create_var(
            name=unique_name.generate("_step_scopes"),
            type=core.VarType.STEP_SCOPES)
        parent_block.append_op(
            type="while",
            inputs={"X": x_names, "Condition": [self.cond_var.name]},
            outputs={"Out": out_names, "StepScopes": [step_scope.name]},
            attrs={"sub_block": while_block.idx,
                   "is_test": self.is_test})


# ---------------------------------------------------------------------------
# DynamicRNN (ref: control_flow.py:1542)
# ---------------------------------------------------------------------------


class DynamicRNN:
    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.status = DynamicRNN.BEFORE_RNN
        self.lod_rank_table = None
        self.max_seq_len = None
        self.step_idx = None
        self.zero_idx = fill_constant(shape=[1], dtype="int64", value=0, force_cpu=True)
        self.mem_dict = {}
        self.output_array = []
        self.outputs = []
        self.cond = self.helper.create_variable_for_type_inference(
            dtype="bool")
        self.cond.stop_gradient = True
        self.while_op = None
        self.input_array = []
        self.mem_link = []

    def step_input(self, x):
        self._assert_in_rnn_block_("step_input")
        parent_block = self._parent_block_()
        from .. import core

        if self.lod_rank_table is None:
            self.lod_rank_table = parent_block.create_var(
                name=unique_name.generate("lod_rank_table"),
                type=core.VarType.LOD_RANK_TABLE)
            self.lod_rank_table.stop_gradient = True
            parent_block.append_op(
                type="lod_rank_table", inputs={"X": [x]},
                outputs={"Out": [self.lod_rank_table]}, attrs={"level": 0})
            self.max_seq_len = parent_block.create_var(
                name=unique_name.generate("dynamic_rnn_max_seq_len"),
                dtype="int64")
            self.max_seq_len.stop_gradient = True
            parent_block.append_op(
                type="max_sequence_len",
                inputs={"RankTable": [self.lod_rank_table]},
                outputs={"Out": [self.max_seq_len]})
            parent_block.append_op(
                type="less_than",
                inputs={"X": [self.step_idx], "Y": [self.max_seq_len]},
                outputs={"Out": [self.cond]})

        input_array = parent_block.create_var(
            name=unique_name.generate("dynamic_rnn_input_array"),
            dtype=x.dtype, type=core.VarType.LOD_TENSOR_ARRAY)
        if x.shape is not None:
            input_array.shape = (-1,) + tuple(x.shape[1:])
        self.input_array.append((input_array, x.dtype))
        parent_block.append_op(
            type="lod_tensor_to_array",
            inputs={"X": [x], "RankTable": [self.lod_rank_table]},
            outputs={"Out": [input_array]})
        return array_read(array=input_array, i=self.step_idx)

    def static_input(self, x):
        self._assert_in_rnn_block_("static_input")
        if self.lod_rank_table is None:
            raise RuntimeError(
                "static_input() must be called after step_input().")
        parent_block = self._parent_block_()
        x_reordered = parent_block.create_var(
            name=unique_name.generate("dynamic_rnn_static_input_reordered"),
            dtype=x.dtype)
        x_reordered.shape = getattr(x, "shape", None)
        parent_block.append_op(
            type="reorder_lod_tensor_by_rank",
            inputs={"X": [x], "RankTable": [self.lod_rank_table]},
            outputs={"Out": [x_reordered]})
        return shrink_memory(x_reordered, self.step_idx, self.lod_rank_table)

    @contextlib.contextmanager
    def block(self):
        if self.status != DynamicRNN.BEFORE_RNN:
            raise ValueError("rnn.block() can only be invoked once")
        self.step_idx = fill_constant(shape=[1], dtype="int64", value=0, force_cpu=True)
        self.step_idx.stop_gradient = False
        self.status = DynamicRNN.IN_RNN
        self.while_op = While(cond=self.cond)
        with self.while_op.block():
            yield
            increment(x=self.step_idx, value=1.0, in_place=True)
            for new_mem, mem_array in self.mem_link:
                array_write(x=new_mem, i=self.step_idx, array=mem_array)
            less_than(x=self.step_idx, y=self.max_seq_len, cond=self.cond)
        self.status = DynamicRNN.AFTER_RNN
        for each_array in self.output_array:
            self.outputs.append(
                array_to_lod_tensor(x=each_array, table=self.lod_rank_table))

    def __call__(self, *args, **kwargs):
        if self.status != DynamicRNN.AFTER_RNN:
            raise ValueError("Dynamic RNN outputs can only be visited "
                             "outside the rnn block.")
        return self.outputs[0] if len(self.outputs) == 1 else self.outputs

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype="float32"):
        self._assert_in_rnn_block_("memory")
        parent_block = self._parent_block_()
        from .. import core

        if init is not None:
            if self.lod_rank_table is None:
                raise ValueError(
                    "DynamicRNN.memory() requires a prior step_input() — "
                    "the rank table defines the shrinking batch order")
            init_tensor = init
            if need_reorder:
                init_reordered = parent_block.create_var(
                    name=unique_name.generate(
                        "dynamic_rnn_mem_init_reordered"), dtype=init.dtype)
                init_reordered.shape = getattr(init, "shape", None)
                parent_block.append_op(
                    type="reorder_lod_tensor_by_rank",
                    inputs={"X": [init_tensor],
                            "RankTable": [self.lod_rank_table]},
                    outputs={"Out": [init_reordered]})
                init_tensor = init_reordered
            mem_array = parent_block.create_var(
                name=unique_name.generate("dynamic_rnn_mem_array"),
                dtype=init.dtype, type=core.VarType.LOD_TENSOR_ARRAY)
            mem_array.shape = getattr(init_tensor, "shape", None)
            parent_block.append_op(
                type="write_to_array",
                inputs={"X": [init_tensor], "I": [self.zero_idx]},
                outputs={"Out": [mem_array]})
            retv = array_read(array=mem_array, i=self.step_idx)
            retv = shrink_memory(x=retv, i=self.step_idx,
                                 table=self.lod_rank_table)
            self.mem_dict[retv.name] = mem_array
            return retv
        else:
            if len(self.input_array) == 0:
                raise ValueError(
                    "step_input should be invoked before memory(shape=...)")
            init = parent_block.create_var(
                name=unique_name.generate("mem_init"), dtype=dtype,
                shape=[-1] + list(shape))
            arr, arr_dtype = self.input_array[0]
            in0 = parent_block.create_var(
                name=unique_name.generate("in0"), dtype=arr_dtype)
            parent_block.append_op(
                type="read_from_array",
                inputs={"X": [arr], "I": [self.zero_idx]},
                outputs={"Out": [in0]})
            parent_block.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [in0]}, outputs={"Out": [init]},
                attrs={"shape": [-1] + list(shape), "value": float(value),
                       "dtype": init.dtype, "input_dim_idx": 0,
                       "output_dim_idx": 0})
            return self.memory(init=init)

    def update_memory(self, ex_mem, new_mem):
        self._assert_in_rnn_block_("update_memory")
        mem_array = self.mem_dict.get(ex_mem.name)
        if mem_array is None:
            raise ValueError("Please invoke memory before update_memory")
        self.mem_link.append((new_mem, mem_array))

    def output(self, *outputs):
        self._assert_in_rnn_block_("output")
        parent_block = self._parent_block_()
        from .. import core

        for each in outputs:
            outside_array = parent_block.create_var(
                name=unique_name.generate("_".join(
                    [self.helper.name, "output_array", each.name])),
                dtype=each.dtype, type=core.VarType.LOD_TENSOR_ARRAY)
            array_write(x=each, i=self.step_idx, array=outside_array)
            self.output_array.append(outside_array)

    def _parent_block_(self):
        prog = self.helper.main_program
        parent_idx = prog.current_block().parent_idx
        assert parent_idx >= 0
        return prog.block(parent_idx)

    def _assert_in_rnn_block_(self, method):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError(f"{method} can only be invoked inside rnn block")


# ---------------------------------------------------------------------------
# StaticRNN (ref: control_flow.py:430 — fixed-length sequences; input layout
# [T, B, ...], stepping over dim 0)
# ---------------------------------------------------------------------------


class StaticRNN:
    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self.seq_len = None
        self.step_idx = None
        self.zero_idx = fill_constant(shape=[1], dtype="int64", value=0, force_cpu=True)
        self.cond = self.helper.create_variable_for_type_inference(
            dtype="bool")
        self.cond.stop_gradient = True
        self.while_op = None
        self.mem_dict = {}
        self.mem_link = []
        self.output_array = []
        self.outputs = []
        self.input_arrays = []
        self._len_const = None

    @contextlib.contextmanager
    def step(self):
        if self.status != StaticRNN.BEFORE_RNN_BLOCK:
            raise ValueError("step() can only be invoked once")
        self.step_idx = fill_constant(shape=[1], dtype="int64", value=0, force_cpu=True)
        self.status = StaticRNN.IN_RNN_BLOCK
        self.while_op = While(cond=self.cond)
        guard = self.while_op.block()
        guard.__enter__()
        try:
            yield
        except BaseException:
            guard.__exit__(*__import__("sys").exc_info())
            raise
        else:
            increment(x=self.step_idx, value=1.0, in_place=True)
            for new_mem, mem_array in self.mem_link:
                array_write(x=new_mem, i=self.step_idx, array=mem_array)
            less_than(x=self.step_idx, y=self._len_const, cond=self.cond)
            self.status = StaticRNN.AFTER_RNN_BLOCK
            guard.__exit__(None, None, None)
            self._finalize()

    def step_input(self, x):
        self._assert_in_rnn_block_("step_input")
        if x.shape is None or x.shape[0] in (None, -1):
            raise ValueError("StaticRNN step_input needs a static sequence "
                             "length as dim 0 ([T, B, ...] layout)")
        seq_len = int(x.shape[0])
        if self.seq_len is None:
            self.seq_len = seq_len
        elif self.seq_len != seq_len:
            raise ValueError("all StaticRNN step inputs must share dim 0")
        parent_block = self._parent_block_()
        if self._len_const is None:
            with _block_guard_ctx(self.helper.main_program, parent_block):
                self._len_const = fill_constant(shape=[1], dtype="int64", value=seq_len,
                                              force_cpu=True)
                less_than(x=self.step_idx, y=self._len_const, cond=self.cond)
        from .. import core

        input_array = parent_block.create_var(
            name=unique_name.generate("static_rnn_input_array"),
            dtype=x.dtype, type=core.VarType.LOD_TENSOR_ARRAY)
        input_array.shape = tuple(x.shape[1:])
        parent_block.append_op(
            type="tensor_array_unstack", inputs={"X": [x]},
            outputs={"Out": [input_array]})
        self.input_arrays.append(input_array)
        return array_read(array=input_array, i=self.step_idx)

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        self._assert_in_rnn_block_("memory")
        parent_block = self._parent_block_()
        from .. import core

        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init or (shape and batch_ref)")
            if not self.input_arrays:
                raise ValueError("memory(batch_ref=...) requires a prior "
                                 "step_input")
            # batch_ref is body-local; derive the batch from the parent-
            # visible step-0 slice of the first input array instead
            arr0 = self.input_arrays[0]
            in0 = parent_block.create_var(
                name=unique_name.generate("static_rnn_in0"),
                dtype=arr0.dtype, shape=getattr(arr0, "shape", None))
            parent_block.append_op(
                type="read_from_array",
                inputs={"X": [arr0], "I": [self.zero_idx]},
                outputs={"Out": [in0]})
            init = parent_block.create_var(
                name=unique_name.generate("static_rnn_mem_init"),
                dtype=batch_ref.dtype,
                shape=[-1] + list(shape[1:] if shape and shape[0] in
                                  (-1, None) else shape))
            mem_shape = list(shape)
            if mem_shape and mem_shape[0] in (-1, None):
                mem_shape = mem_shape[1:]
            parent_block.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [in0]}, outputs={"Out": [init]},
                attrs={"shape": [-1] + mem_shape,
                       "value": float(init_value), "dtype": init.dtype,
                       "input_dim_idx": 0,
                       "output_dim_idx": init_batch_dim_idx})
        mem_array = parent_block.create_var(
            name=unique_name.generate("static_rnn_mem_array"),
            dtype=init.dtype, type=core.VarType.LOD_TENSOR_ARRAY)
        mem_array.shape = getattr(init, "shape", None)
        parent_block.append_op(
            type="write_to_array",
            inputs={"X": [init], "I": [self.zero_idx]},
            outputs={"Out": [mem_array]})
        retv = array_read(array=mem_array, i=self.step_idx)
        self.mem_dict[retv.name] = mem_array
        return retv

    def update_memory(self, mem, var):
        self._assert_in_rnn_block_("update_memory")
        mem_array = self.mem_dict.get(mem.name)
        if mem_array is None:
            raise ValueError("update_memory: unknown memory")
        self.mem_link.append((var, mem_array))

    def step_output(self, o):
        self._assert_in_rnn_block_("step_output")
        parent_block = self._parent_block_()
        from .. import core

        out_array = parent_block.create_var(
            name=unique_name.generate("static_rnn_output_array"),
            dtype=o.dtype, type=core.VarType.LOD_TENSOR_ARRAY)
        array_write(x=o, i=self.step_idx, array=out_array)
        self.output_array.append(out_array)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _finalize(self):
        helper = LayerHelper("static_rnn_out")
        for arr in self.output_array:
            out = helper.create_variable_for_type_inference(dtype=arr.dtype)
            helper.append_op(type="tensor_array_stack",
                             inputs={"X": [arr]}, outputs={"Out": [out]})
            self.outputs.append(out)

    def __call__(self, *args, **kwargs):
        if self.status != StaticRNN.AFTER_RNN_BLOCK:
            raise ValueError("outputs readable only after the step block")
        return self.outputs[0] if len(self.outputs) == 1 else self.outputs

    def _parent_block_(self):
        prog = self.helper.main_program
        return prog.block(prog.current_block().parent_idx)

    def _assert_in_rnn_block_(self, method):
        if self.status != StaticRNN.IN_RNN_BLOCK:
            raise ValueError(f"{method} must be called inside step()")


@contextlib.contextmanager
def _block_guard_ctx(program, block):
    """Temporarily append ops into an outer block."""
    saved = program.current_block_idx
    program.current_block_idx = block.idx
    try:
        yield
    finally:
        program.current_block_idx = saved


# ---------------------------------------------------------------------------
# IfElse / Switch
# ---------------------------------------------------------------------------


class IfElse:
    """ref: control_flow.py IfElse — split rows by a bool mask, run both
    branches on their subsets, merge (executor's eager tier)."""

    OUT_IF_ELSE_BLOCKS = 0
    IN_IF_ELSE_TRUE_BLOCKS = 1
    IN_IF_ELSE_FALSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self.input_table = {}
        self.status = IfElse.OUT_IF_ELSE_BLOCKS
        self.output_table = [[], []]  # [false, true]

    def input(self, x):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("input() must be inside true_block/false_block")
        branch = self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS
        if x.name not in self.input_table:
            helper = LayerHelper("split_lod_tensor")
            out_true = helper.create_variable_for_type_inference(x.dtype)
            out_false = helper.create_variable_for_type_inference(x.dtype)
            helper.append_op(
                type="split_lod_tensor",
                inputs={"X": [x], "Mask": [self.cond]},
                outputs={"OutTrue": [out_true], "OutFalse": [out_false]},
                attrs={"level": 0})
            self.input_table[x.name] = (out_true, out_false)
        out_true, out_false = self.input_table[x.name]
        return out_true if branch else out_false

    @contextlib.contextmanager
    def true_block(self):
        self.status = IfElse.IN_IF_ELSE_TRUE_BLOCKS
        yield
        self.status = IfElse.OUT_IF_ELSE_BLOCKS

    @contextlib.contextmanager
    def false_block(self):
        self.status = IfElse.IN_IF_ELSE_FALSE_BLOCKS
        yield
        self.status = IfElse.OUT_IF_ELSE_BLOCKS

    def output(self, *outs):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("output() must be inside a branch block")
        branch = 1 if self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS else 0
        self.output_table[branch].extend(outs)

    def __call__(self):
        if self.status != IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("IfElse::__call__ must be out of sub-blocks")
        false_outs, true_outs = self.output_table
        if len(false_outs) != len(true_outs):
            raise ValueError("true/false blocks must declare equal outputs")
        rets = []
        helper = LayerHelper("merge_lod_tensor")
        for t, f in zip(true_outs, false_outs):
            out = helper.create_variable_for_type_inference(t.dtype)
            helper.append_op(
                type="merge_lod_tensor",
                inputs={"InTrue": [t], "InFalse": [f], "Mask": [self.cond],
                        "X": [self.cond]},
                outputs={"Out": [out]}, attrs={"level": 0})
            rets.append(out)
        return rets[0] if len(rets) == 1 else rets


class ConditionalBlock:
    """ref: conditional_block_op.cc wrapper used by Switch."""

    def __init__(self, inputs, is_scalar_condition=False, name=None):
        self.helper = LayerHelper("conditional_block", name=name)
        self.inputs = inputs
        self.is_scalar_condition = is_scalar_condition

    @contextlib.contextmanager
    def block(self):
        prog = self.helper.main_program
        prog._create_block()
        yield
        cond_block = prog.current_block()
        prog._rollback()
        parent_block = prog.current_block()

        written = set()
        in_names, out_names = [], []
        for op in cond_block.ops:
            for n in op.input_arg_names:
                if n and n not in written and n not in in_names and \
                        parent_block._has_var_recursive(n):
                    in_names.append(n)
            for n in op.output_arg_names:
                if not n:
                    continue
                written.add(n)
                if parent_block._has_var_recursive(n) and n not in out_names:
                    out_names.append(n)
        from .. import core

        step_scope = parent_block.create_var(
            name=unique_name.generate("_cond_scopes"),
            type=core.VarType.STEP_SCOPES)
        parent_block.append_op(
            type="conditional_block",
            inputs={"Cond": [c.name for c in self.inputs],
                    "Input": in_names},
            outputs={"Out": out_names, "Scope": [step_scope.name]},
            attrs={"sub_block": cond_block.idx,
                   "is_scalar_condition": self.is_scalar_condition})


class Switch:
    """ref: control_flow.py Switch — scalar-condition case chain built on
    conditional_block (conditions must be concrete at trace time)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.inside_scope = False
        self.pre_not_conditions = []

    @contextlib.contextmanager
    def case(self, condition):
        if not self.inside_scope:
            raise ValueError("case should be called inside with")
        from .ops import logical_and, logical_not

        if len(self.pre_not_conditions) == 0:
            cond_block = ConditionalBlock([condition],
                                          is_scalar_condition=True)
            not_cond = logical_not(x=condition)
            self.pre_not_conditions.append(not_cond)
        else:
            pre_cond_num = len(self.pre_not_conditions)
            pre_not_cond = self.pre_not_conditions[pre_cond_num - 1]
            new_not_cond = logical_and(
                x=pre_not_cond, y=logical_not(x=condition))
            self.pre_not_conditions.append(new_not_cond)
            cond_block = ConditionalBlock(
                [logical_and(x=pre_not_cond, y=condition)],
                is_scalar_condition=True)
        with cond_block.block():
            yield

    @contextlib.contextmanager
    def default(self):
        pre_cond_num = len(self.pre_not_conditions)
        if pre_cond_num == 0:
            raise ValueError("there should be at least one condition")
        cond_block = ConditionalBlock(
            [self.pre_not_conditions[pre_cond_num - 1]],
            is_scalar_condition=True)
        with cond_block.block():
            yield

    def __enter__(self):
        self.inside_scope = True
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.inside_scope = False
        return exc_type is None
