"""Detection layer API (ref: python/paddle/fluid/layers/detection.py —
prior_box :449, box_coder :129, iou_similarity :109, bipartite_match :584,
target_assign :651, multiclass_nms-in-detection_output :93, ssd_loss :734,
roi_pool lives in layers/nn.py in the reference)."""

from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = [
    "prior_box", "box_coder", "iou_similarity", "bipartite_match",
    "target_assign", "multiclass_nms", "detection_output", "roi_pool",
    "anchor_generator", "polygon_box_transform",
    "detection_map", "rpn_target_assign", "generate_proposals",
    "generate_proposal_labels", "ssd_loss", "multi_box_head",
]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", **locals())
    dtype = helper.input_dtype("input")
    boxes = helper.create_variable_for_type_inference(dtype)
    var = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="prior_box", inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios),
               "variances": list(variance), "flip": flip, "clip": clip,
               "step_w": steps[0], "step_h": steps[1], "offset": offset,
               "min_max_aspect_ratios_order": min_max_aspect_ratios_order})
    # priors are constants of the data path (ref prior_box layer sets
    # stop_gradient); without this, backward demands a grad no op provides
    boxes.stop_gradient = True
    var.stop_gradient = True
    return boxes, var


def anchor_generator(input, anchor_sizes, aspect_ratios=(1.0,),
                     variance=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    helper = LayerHelper("anchor_generator", **locals())
    dtype = helper.input_dtype("input")
    anchors = helper.create_variable_for_type_inference(dtype)
    var = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [var]},
        attrs={"anchor_sizes": list(anchor_sizes),
               "aspect_ratios": list(aspect_ratios),
               "variances": list(variance), "stride": list(stride),
               "offset": offset})
    anchors.stop_gradient = True
    var.stop_gradient = True
    return anchors, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    helper = LayerHelper("box_coder", **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype("target_box"))
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(type="box_coder", inputs=inputs,
                     outputs={"OutputBox": [out]},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized})
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", **locals())
    out = helper.create_variable_for_type_inference(helper.input_dtype("x"))
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"box_normalized": box_normalized})
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    helper = LayerHelper("bipartite_match", **locals())
    match_indices = helper.create_variable_for_type_inference("int32")
    match_dist = helper.create_variable_for_type_inference(
        helper.input_dtype("dist_matrix"))
    helper.append_op(
        type="bipartite_match", inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [match_indices],
                 "ColToRowMatchDist": [match_dist]},
        attrs={"match_type": match_type, "dist_threshold": dist_threshold})
    return match_indices, match_dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    helper = LayerHelper("target_assign", **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype("input"))
    out_weight = helper.create_variable_for_type_inference("float32")
    inputs = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices]
    helper.append_op(type="target_assign", inputs=inputs,
                     outputs={"Out": [out], "OutWeight": [out_weight]},
                     attrs={"mismatch_value": mismatch_value})
    return out, out_weight


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=-1,
                   keep_top_k=-1, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    helper = LayerHelper("multiclass_nms", **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype("bboxes"))
    helper.append_op(
        type="multiclass_nms", inputs={"BBoxes": [bboxes],
                                       "Scores": [scores]},
        outputs={"Out": [out]},
        attrs={"score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
               "normalized": normalized, "nms_eta": nms_eta,
               "background_label": background_label})
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """ref: layers/detection.py detection_output:93 — decode + NMS."""
    from . import nn as _nn

    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    return multiclass_nms(decoded, scores,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold, nms_eta=nms_eta,
                          background_label=background_label)


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    helper = LayerHelper("roi_pool", **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype("input"))
    helper.append_op(type="roi_pool",
                     inputs={"X": [input], "ROIs": [rois]},
                     outputs={"Out": [out]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype("input"))
    helper.append_op(type="polygon_box_transform", inputs={"Input": [input]},
                     outputs={"Output": [out]})
    return out

def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None,
                  out_states=None, ap_version="integral"):
    """mAP evaluation op wrapper (ref layers/detection.py detection_map
    :315 — default overlap 0.3).  For dataset-level mAP pass
    ``input_states`` (prev accumulators) and ``out_states`` (vars to
    receive the updated accumulators), then feed out_states back in as
    input_states next batch — the reference's chaining contract."""
    helper = LayerHelper("detection_map", **locals())
    m = helper.create_variable_for_type_inference("float32")
    m.shape = (1,)
    if out_states is not None:
        acc_pos, acc_tp, acc_fp = out_states
    else:
        acc_pos = helper.create_variable_for_type_inference("float32")
        acc_tp = helper.create_variable_for_type_inference("float32")
        acc_fp = helper.create_variable_for_type_inference("float32")
    inputs = {"DetectRes": [detect_res], "Label": [label]}
    if input_states is not None:
        inputs["PosCount"] = [input_states[0]]
        inputs["TruePos"] = [input_states[1]]
        inputs["FalsePos"] = [input_states[2]]
    helper.append_op(
        type="detection_map", inputs=inputs,
        outputs={"MAP": [m], "AccumPosCount": [acc_pos],
                 "AccumTruePos": [acc_tp], "AccumFalsePos": [acc_fp]},
        attrs={"class_num": class_num,
               "background_label": background_label,
               "overlap_threshold": overlap_threshold,
               "evaluate_difficult": evaluate_difficult,
               "ap_type": ap_version})
    if out_states is not None:
        return m, acc_pos, acc_tp, acc_fp
    return m


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info, rpn_batch_size_per_im=256,
                      rpn_straddle_thresh=0.0, rpn_fg_fraction=0.5,
                      rpn_positive_overlap=0.7, rpn_negative_overlap=0.3,
                      use_random=True):
    """RPN training-target assignment (ref layers/detection.py
    rpn_target_assign, operators/detection/rpn_target_assign_op.cc)."""
    helper = LayerHelper("rpn_target_assign", **locals())
    loc_index = helper.create_variable_for_type_inference("int64")
    score_index = helper.create_variable_for_type_inference("int64")
    target_label = helper.create_variable_for_type_inference("int64")
    target_bbox = helper.create_variable_for_type_inference(
        anchor_box.dtype)
    helper.append_op(
        type="rpn_target_assign",
        inputs={"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
                "IsCrowd": [is_crowd], "ImInfo": [im_info]},
        outputs={"LocationIndex": [loc_index],
                 "ScoreIndex": [score_index],
                 "TargetLabel": [target_label],
                 "TargetBBox": [target_bbox]},
        attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
               "rpn_straddle_thresh": rpn_straddle_thresh,
               "rpn_positive_overlap": rpn_positive_overlap,
               "rpn_negative_overlap": rpn_negative_overlap,
               "rpn_fg_fraction": rpn_fg_fraction,
               "use_random": use_random})
    # gather the predictions the assignment selected (ref :186-194)
    from .nn import gather, reshape

    cls_logits = reshape(cls_logits, shape=[-1, 1])
    bbox_pred = reshape(bbox_pred, shape=[-1, 4])
    predicted_cls_logits = gather(cls_logits, score_index)
    predicted_bbox_pred = gather(bbox_pred, loc_index)
    return (predicted_cls_logits, predicted_bbox_pred, target_label,
            target_bbox)


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0):
    """RPN proposal generation (ref layers/detection.py generate_proposals,
    operators/detection/generate_proposals_op.cc)."""
    helper = LayerHelper("generate_proposals", **locals())
    rois = helper.create_variable_for_type_inference(scores.dtype)
    roi_probs = helper.create_variable_for_type_inference(scores.dtype)
    helper.append_op(
        type="generate_proposals",
        inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                "ImInfo": [im_info], "Anchors": [anchors],
                "Variances": [variances]},
        outputs={"RpnRois": [rois], "RpnRoiProbs": [roi_probs]},
        attrs={"pre_nms_topN": pre_nms_top_n,
               "post_nms_topN": post_nms_top_n, "nms_thresh": nms_thresh,
               "min_size": min_size, "eta": eta})
    return rois, roi_probs


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=True):
    """Sample + label RoIs for the detection head (ref layers/detection.py
    generate_proposal_labels, generate_proposal_labels_op.cc)."""
    helper = LayerHelper("generate_proposal_labels", **locals())
    dtype = rpn_rois.dtype
    rois = helper.create_variable_for_type_inference(dtype)
    labels_int32 = helper.create_variable_for_type_inference("int32")
    bbox_targets = helper.create_variable_for_type_inference(dtype)
    bbox_inside = helper.create_variable_for_type_inference(dtype)
    bbox_outside = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="generate_proposal_labels",
        inputs={"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
                "IsCrowd": [is_crowd], "GtBoxes": [gt_boxes],
                "ImInfo": [im_info]},
        outputs={"Rois": [rois], "LabelsInt32": [labels_int32],
                 "BboxTargets": [bbox_targets],
                 "BboxInsideWeights": [bbox_inside],
                 "BboxOutsideWeights": [bbox_outside]},
        attrs={"batch_size_per_im": batch_size_per_im,
               "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
               "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo,
               "bbox_reg_weights": list(bbox_reg_weights),
               "class_nums": class_nums, "use_random": use_random})
    return (rois, labels_int32, bbox_targets, bbox_inside, bbox_outside)



def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None):
    """SSD multibox loss (ref: layers/detection.py ssd_loss — match gt to
    priors, mine hard negatives, weighted smooth-l1 + softmax CE).

    location [N, Np, 4]; confidence [N, Np, C]; gt_box/gt_label LoD
    tensors [Ng, 4]/[Ng, 1]; prior_box [Np, 4].  Returns the [N, 1]
    per-image loss (summed over priors, optionally normalized by the
    positive count).
    """
    from . import nn as _nn
    from . import tensor as _tensor

    helper = LayerHelper("ssd_loss", **locals())
    if mining_type != "max_negative":
        raise ValueError("Only mining_type == 'max_negative' is supported")
    num_prior = confidence.shape[1]

    def to_2d(var):
        return _nn.flatten(var, axis=2)

    # 1. match gt to priors on IoU
    iou = iou_similarity(x=gt_box, y=prior_box)
    matched_indices, matched_dist = bipartite_match(iou, match_type,
                                                    overlap_threshold)

    # 2. provisional confidence loss drives hard-negative mining
    # (this build's target_assign takes X as LoD rows [Ng, P, K])
    gt_label = _nn.reshape(gt_label, [-1, 1, 1])
    gt_label.stop_gradient = True
    target_label, _ = target_assign(gt_label, matched_indices,
                                    mismatch_value=background_label)
    conf2d = to_2d(confidence)
    target_label_2d = _tensor.cast(to_2d(target_label), "int64")
    target_label_2d.stop_gradient = True
    conf_loss = _nn.softmax_with_cross_entropy(conf2d, target_label_2d)
    conf_loss = _nn.reshape(conf_loss, [-1, num_prior])
    conf_loss.stop_gradient = True

    neg_indices = helper.create_variable_for_type_inference("int32")
    updated_indices = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="mine_hard_examples",
        inputs={"ClsLoss": [conf_loss], "MatchIndices": [matched_indices],
                "MatchDist": [matched_dist]},
        outputs={"NegIndices": [neg_indices],
                 "UpdatedMatchIndices": [updated_indices]},
        attrs={"neg_pos_ratio": neg_pos_ratio,
               "neg_dist_threshold": neg_overlap,
               "mining_type": mining_type,
               "sample_size": sample_size or 0})

    # 3. regression targets: encoded gt assigned to matched priors
    encoded = box_coder(prior_box=prior_box, prior_box_var=prior_box_var,
                        target_box=gt_box,
                        code_type="encode_center_size")
    target_bbox, target_loc_weight = target_assign(
        encoded, updated_indices, mismatch_value=background_label)
    # 4. classification targets incl. mined negatives
    target_label, target_conf_weight = target_assign(
        gt_label, updated_indices, negative_indices=neg_indices,
        mismatch_value=background_label)

    target_label = _tensor.cast(to_2d(target_label), "int64")
    target_label.stop_gradient = True
    conf_loss = _nn.softmax_with_cross_entropy(conf2d, target_label)
    tcw = _nn.reshape(target_conf_weight, [-1, 1])
    tcw.stop_gradient = True
    conf_loss = _nn.elementwise_mul(conf_loss, tcw)

    loc2d = to_2d(location)
    tb = to_2d(target_bbox)
    tb.stop_gradient = True
    loc_loss = _nn.smooth_l1(loc2d, tb)
    tlw = _nn.reshape(target_loc_weight, [-1, 1])
    tlw.stop_gradient = True
    loc_loss = _nn.elementwise_mul(loc_loss, tlw)

    loss = _nn.elementwise_add(
        _nn.scale(conf_loss, scale=float(conf_loss_weight)),
        _nn.scale(loc_loss, scale=float(loc_loss_weight)))
    loss = _nn.reshape(loss, [-1, num_prior])
    loss = _nn.reduce_sum(loss, dim=1, keep_dim=True)
    if normalize:
        normalizer = _nn.reduce_sum(target_loc_weight)
        loss = _nn.elementwise_div(loss, normalizer)
    return loss


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (ref: layers/detection.py multi_box_head): per
    feature map, a conv pair predicts box offsets and class scores for
    that map's priors; priors come from prior_box.  Returns
    (mbox_locs [N, P, 4], mbox_confs [N, P, C], boxes [P, 4],
    variances [P, 4]) concatenated over maps.
    """
    from . import nn as _nn
    from . import tensor as _tensor

    n_maps = len(inputs)
    if min_sizes is None:
        # the reference's ratio schedule (ref multi_box_head: min_ratio..
        # max_ratio split across maps, first map pinned to 10%/20%);
        # degenerate map counts fall back to an even split
        min_sizes, max_sizes = [], []
        if n_maps > 2:
            step_r = int((max_ratio - min_ratio) / (n_maps - 2))
            for r in range(min_ratio, max_ratio + 1, step_r):
                min_sizes.append(base_size * r / 100.0)
                max_sizes.append(base_size * (r + step_r) / 100.0)
            min_sizes = [base_size * 0.10] + min_sizes
            max_sizes = [base_size * 0.20] + max_sizes
        else:
            span = (max_ratio - min_ratio) / max(1, n_maps)
            for i in range(n_maps):
                lo = min_ratio + span * i
                min_sizes.append(base_size * lo / 100.0)
                max_sizes.append(base_size * (lo + span) / 100.0)
        min_sizes = min_sizes[:n_maps]
        max_sizes = max_sizes[:n_maps]

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        mins_l = mins if isinstance(mins, (list, tuple)) else [mins]
        maxs_l = (maxs if isinstance(maxs, (list, tuple))
                  else ([maxs] if maxs else []))
        ars = aspect_ratios[i]
        ars_l = list(ars) if isinstance(ars, (list, tuple)) else [ars]
        step = (steps[i] if steps else
                ((step_w[i] if step_w else 0.0),
                 (step_h[i] if step_h else 0.0)))
        if not isinstance(step, (list, tuple)):
            step = (step, step)
        # priors per location: the EXACT count the prior_box op emits
        from ...ops.detection_ops import (_expand_aspect_ratios,
                                          _prior_whs)

        num_priors = len(_prior_whs(
            [float(v) for v in mins_l],
            [float(v) for v in maxs_l],
            _expand_aspect_ratios(ars_l, flip),
            min_max_aspect_ratios_order))

        loc = _nn.conv2d(feat, num_filters=num_priors * 4,
                         filter_size=kernel_size, padding=pad,
                         stride=stride)
        conf = _nn.conv2d(feat, num_filters=num_priors * num_classes,
                          filter_size=kernel_size, padding=pad,
                          stride=stride)
        # priors are generated from the CONV OUTPUT map, not the input
        # feature map: with kernel_size>1/pad=0 or stride>1 the conv
        # shrinks the map, and the prediction grid (which the priors must
        # tile one-to-one) is the conv output.  Generating both from the
        # same tensor keeps mbox_locs/confs and boxes counts in agreement
        # for every kernel/pad/stride combination.
        boxes, var = prior_box(loc, image, mins_l, maxs_l or None, ars_l,
                               variance, flip, clip, step, offset,
                               min_max_aspect_ratios_order=
                               min_max_aspect_ratios_order)
        # NCHW -> [N, H*W*num_priors, 4 or C] (static prior count so the
        # ssd_loss reshape chain keeps concrete shapes)
        fh, fw = loc.shape[2], loc.shape[3]
        p_i = int(fh) * int(fw) * int(num_priors)
        loc = _nn.transpose(loc, perm=[0, 2, 3, 1])
        conf = _nn.transpose(conf, perm=[0, 2, 3, 1])
        locs.append(_nn.reshape(loc, [-1, p_i, 4]))
        confs.append(_nn.reshape(conf, [-1, p_i, num_classes]))
        boxes_all.append(_nn.reshape(boxes, [-1, 4]))
        vars_all.append(_nn.reshape(var, [-1, 4]))

    mbox_locs = _tensor.concat(locs, axis=1)
    mbox_confs = _tensor.concat(confs, axis=1)
    boxes = _tensor.concat(boxes_all, axis=0)
    variances = _tensor.concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, boxes, variances
