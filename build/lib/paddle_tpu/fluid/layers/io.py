"""IO layers: data declarations + reader pipeline (ref: python/paddle/
fluid/layers/io.py — data :38, py_reader :474, open_recordio_file :345,
double_buffer :891).

TPU design: the reference's reader ops pull from a LoDTensorBlockingQueue
inside the C++ executor loop.  Here the queue hand-off happens on the host
*before* the jitted step (host infeed): the Executor sees a ``read`` op,
pops a packed batch from the reader's native blocking queue
(paddle_tpu/native), and injects it as the step's feed — the device-side
program stays a pure static-shape XLA computation.  double_buffer is a
queue-capacity hint (host->device overlap comes from jax's async dispatch).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from .. import core, unique_name
from ..framework import default_main_program

__all__ = ["data", "py_reader", "read_file", "open_recordio_file",
           "open_files", "random_data_generator", "Preprocessor",
           "ParallelDo", "batch",
           "shuffle", "double_buffer", "create_py_reader_by_data"]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=None, stop_gradient=True):
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = default_main_program().current_block()
    return block.create_var(
        name=name, shape=shape, dtype=core.convert_dtype(dtype),
        lod_level=lod_level, stop_gradient=stop_gradient, is_data=True)


# ---------------------------------------------------------------------------
# reader runtime state (host side)
# ---------------------------------------------------------------------------

_READERS: Dict[str, "ReaderState"] = {}


def _reader_state(name: str) -> "ReaderState":
    try:
        return _READERS[name]
    except KeyError:
        raise RuntimeError(f"reader '{name}' has no runtime state — was it "
                           f"created by py_reader/open_recordio_file?") \
            from None


class ReaderState:
    """Host-side state of one reader var: a native blocking queue plus an
    optional producer thread (ref: reader/create_py_reader_op.cc +
    lod_tensor_blocking_queue.h, as a host-infeed design).

    Sources yield *item lists* ([(np array, lod offsets), ...], one item
    per slot); the producer thread applies the shuffle/batch decorators,
    packs, and pushes.  Producer exceptions re-raise at next_batch (not
    silently as EOF)."""

    def __init__(self, name: str, capacity: int, shapes, dtypes, lod_levels,
                 batch_size: Optional[int] = None):
        from ...native import BlockingQueue

        self.name = name
        self.queue = BlockingQueue(capacity)
        self.shapes = shapes
        self.dtypes = dtypes
        self.lod_levels = lod_levels
        self.batch_size = batch_size
        self.shuffle_buf = 0
        self._producer = None
        self._source = None          # callable -> iterable of item lists
        self._started = False
        self._error = None

    # -- user surface (mirrors ref py_reader methods) --
    def _minibatch_items(self, minibatch):
        """list of sample tuples -> item list, via the DataFeeder
        converters (one converter per slot, fed every sample)."""
        from ..data_feeder import DataToLoDTensorConverter
        from ..lod_tensor import LoDTensor

        convs = []
        for shape, dtype, lod_level in zip(self.shapes, self.dtypes,
                                           self.lod_levels):
            # full declared shape (incl. -1 batch dim): the converter
            # reshapes the stacked samples to it
            convs.append(DataToLoDTensorConverter(None, lod_level, shape,
                                                  dtype))
        for sample in minibatch:
            for conv, slot in zip(convs, sample):
                conv.feed(slot)
        items = []
        for conv in convs:
            done = conv.done()
            if isinstance(done, LoDTensor):
                items.append((np.asarray(done), done.lod()))
            else:
                items.append((np.asarray(done), ()))
        return items

    def decorate_paddle_reader(self, reader, places=None):
        """reader: callable -> iterable of MINIBATCHES (lists of sample
        tuples — i.e. the output of paddle.batch(...)), the reference
        decorate_paddle_reader contract."""

        def source():
            for minibatch in reader():
                yield self._minibatch_items(minibatch)

        self._source = source

    def decorate_sample_reader(self, reader, places=None):
        """reader yields single sample tuples; combine with
        layers.batch(reader_var, n) to form minibatches."""

        def source():
            for sample in reader():
                yield self._minibatch_items([sample])

        self._source = source

    def decorate_tensor_provider(self, provider):
        """provider: callable -> iterable of batches: lists of arrays,
        LoDTensors, or (array, recursive_seq_lens) tuples."""

        def source():
            from ..lod_tensor import LoDTensor, _lengths_to_offsets

            for batch in provider():
                items = []
                for v in batch:
                    if isinstance(v, LoDTensor):
                        items.append((np.asarray(v), v.lod()))
                    elif isinstance(v, tuple) and len(v) == 2:
                        arr, lens = v
                        lod = tuple(tuple(_lengths_to_offsets(l))
                                    for l in lens)
                        items.append((np.asarray(arr), lod))
                    else:
                        items.append((np.asarray(v), ()))
                yield items

        self._source = source

    def _decorated(self):
        """Apply shuffle/batch decorators over the source's item lists."""
        import random

        merger = _BatchMerger(self.batch_size) if self.batch_size else None
        buf = []

        def emit(items):
            if merger is None:
                return items
            return merger.add(items)

        for items in self._source():
            if self.shuffle_buf:
                buf.append(items)
                if len(buf) < self.shuffle_buf:
                    continue
                items = buf.pop(random.randrange(len(buf)))
            out = emit(items)
            if out is not None:
                yield out
        while buf:
            out = emit(buf.pop(random.randrange(len(buf))))
            if out is not None:
                yield out
        if merger is not None:
            rest = merger.flush()
            if rest is not None:
                yield rest

    def start(self):
        if self._source is None:
            raise RuntimeError("reader has no data source; call "
                               "decorate_paddle_reader/tensor_provider")
        if self._started:
            return
        self.queue.reopen()
        self._started = True
        self._error = None

        def run():
            from ...native.tensor_pack import pack_batch

            try:
                for items in self._decorated():
                    if not self.queue.push(pack_batch(items)):
                        return           # closed under us (reset)
            except BaseException as e:   # surfaces at next_batch
                self._error = e
            finally:
                self.queue.close()

        self._producer = threading.Thread(target=run, daemon=True)
        self._producer.start()

    def reset(self):
        self.queue.close()
        if self._producer is not None:
            self._producer.join(timeout=5)
        self._producer = None
        self._started = False

    # -- executor surface --
    def next_batch(self):
        """list of (np array, lod offsets) — raises EOFException."""
        from ...native.tensor_pack import unpack_batch

        packed = self.queue.pop()
        if packed is None:
            self._started = False
            if self._error is not None:
                err, self._error = self._error, None
                raise RuntimeError(
                    f"reader {self.name}: producer thread failed") from err
            raise core.EOFException(f"reader {self.name} exhausted")
        return unpack_batch(packed)


class _ReaderVar:
    """The Variable facade with reader controls attached."""

    def __new__(cls, var, state):
        var._reader_state = state
        var.start = state.start
        var.reset = state.reset
        var.decorate_paddle_reader = state.decorate_paddle_reader
        var.decorate_tensor_provider = state.decorate_tensor_provider
        return var


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """ref: layers/io.py:474 — returns a reader variable; feed it with
    decorate_paddle_reader()/decorate_tensor_provider(), then start()."""
    block = default_main_program().current_block()
    name = name or unique_name.generate("py_reader")
    shapes = [list(s) for s in shapes]
    dtypes = [core.convert_dtype(d) for d in dtypes]
    lod_levels = list(lod_levels or [0] * len(shapes))
    reader = block.create_var(name=name, type=core.VarType.READER)
    state = ReaderState(name, capacity, shapes, dtypes, lod_levels)
    _READERS[name] = state
    block.append_op(type="create_py_reader", inputs={},
                    outputs={"Out": [reader]},
                    attrs={"shape_concat": [d for s in shapes for d in s],
                           "lod_levels": lod_levels,
                           "capacity": capacity})
    return _ReaderVar(reader, state)


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    shapes = [list(v.shape) for v in feed_list]
    dtypes = [v.dtype for v in feed_list]
    lod_levels = [v.lod_level for v in feed_list]
    return py_reader(capacity, shapes, dtypes, lod_levels, name,
                     use_double_buffer)


def read_file(reader):
    """ref: layers/io.py read_file — materialize the reader's outputs as
    data variables fed by the executor's host-infeed pop."""
    state = _reader_state(reader.name)
    block = default_main_program().current_block()
    outs = []
    for i, (shape, dtype, lod_level) in enumerate(
            zip(state.shapes, state.dtypes, state.lod_levels)):
        v = block.create_var(name=f"{reader.name}__out_{i}", shape=shape,
                             dtype=dtype, lod_level=lod_level,
                             stop_gradient=True, is_data=True)
        outs.append(v)
    block.append_op(type="read", inputs={"Reader": [reader]},
                    outputs={"Out": [v.name for v in outs]})
    return outs[0] if len(outs) == 1 else outs


def open_recordio_file(filename, shapes, dtypes, lod_levels=None,
                       pass_num=1, for_parallel=False):
    """ref: layers/io.py:345 — a reader over a recordio dataset file
    written by fluid.recordio_writer (each record = one packed sample)."""
    rd = py_reader(capacity=64, shapes=shapes, dtypes=dtypes,
                   lod_levels=lod_levels)
    state = rd._reader_state

    def source():
        from ...native import RecordIOScanner
        from ...native.tensor_pack import unpack_batch

        for _ in range(pass_num):
            with RecordIOScanner(filename) as sc:
                for rec in sc:
                    yield list(unpack_batch(rec))

    state._source = source
    return rd


def open_files(filenames, shapes, dtypes, lod_levels=None,
               thread_num=2, buffer_size=256, pass_num=1):
    """ref: layers/io.py open_files — one reader over MANY recordio shards.
    Backed by the native multi-threaded prefetcher (native/prefetch.cc),
    so file IO/decompression runs in C++ worker threads like the
    reference's open_files + multi-thread reader stack."""
    rd = py_reader(capacity=buffer_size, shapes=shapes, dtypes=dtypes,
                   lod_levels=lod_levels)
    state = rd._reader_state

    def source():
        from ...native import PrefetchReader
        from ...native.tensor_pack import unpack_batch

        for _ in range(pass_num):
            for rec in PrefetchReader(list(filenames),
                                      n_threads=thread_num,
                                      capacity=buffer_size):
                yield list(unpack_batch(rec))

    state._source = source
    return rd


def random_data_generator(low, high, shapes, lod_levels=None,
                          for_parallel=False):
    """ref: reader/create_random_data_generator_op.cc — a reader yielding
    uniform-random float batches forever (fixtures/benchmarks)."""
    dtypes = ["float32"] * len(shapes)
    rd = py_reader(capacity=16, shapes=shapes, dtypes=dtypes,
                   lod_levels=lod_levels)
    state = rd._reader_state

    def source():
        rng = np.random.RandomState(0)
        while True:
            yield [(rng.uniform(low, high, size=[max(1, d if d not in
                    (-1, None) else 1) for d in shape])
                    .astype(np.float32), None)
                   for shape in shapes]

    state._source = source
    return rd


class _BatchMerger:
    """Merge per-sample records into batches (concat dim 0 + lod merge)."""

    def __init__(self, batch_size: int):
        self.batch_size = batch_size
        self.samples: List = []

    def add(self, items):
        self.samples.append(items)
        if len(self.samples) >= self.batch_size:
            return self.flush()
        return None

    def flush(self):
        if not self.samples:
            return None
        n_slots = len(self.samples[0])
        merged = []
        for i in range(n_slots):
            arrs = [s[i][0] for s in self.samples]
            lods = [s[i][1] for s in self.samples]
            data = np.concatenate(arrs, axis=0)
            if lods[0]:
                levels = []
                for lv in range(len(lods[0])):
                    off = [0]
                    for l in lods:
                        base = off[-1]
                        off.extend(base + int(x) for x in l[lv][1:])
                    levels.append(tuple(off))
                merged.append((data, tuple(levels)))
            else:
                merged.append((data, ()))
        self.samples = []
        return merged


def batch(reader, batch_size):
    """ref: layers/io.py batch — group per-sample records into batches."""
    _reader_state(reader.name).batch_size = batch_size
    return reader


def shuffle(reader, buffer_size):
    """ref: layers/io.py shuffle — bounded-buffer shuffling."""
    _reader_state(reader.name).shuffle_buf = buffer_size
    return reader


def double_buffer(reader, place=None, name=None):
    """ref: layers/io.py:891 — on TPU, host->device overlap comes from
    jax's async dispatch; keep as a capacity hint."""
    return reader

class Preprocessor:
    """In-pipeline batch transform (ref: layers/io.py Preprocessor — a
    sub-program applied to every batch a reader produces).  The user
    defines the transform as IR inside the ``block()`` context; each
    batch then runs through that (jit-cached) sub-program before
    reaching the training program's `read` op.

    Example::

        pre = fluid.layers.Preprocessor(reader)
        with pre.block():
            img, lbl = pre.inputs()
            img = fluid.layers.scale(img, scale=1.0 / 255.0)
            pre.outputs(img, lbl)
        x, y = fluid.layers.read_file(pre())
    """

    def __init__(self, reader, name=None):
        self._reader = reader
        self._state = reader._reader_state
        self._prog = None
        self._in_vars = None
        self._out_vars = None

    def block(self):
        import contextlib

        from ..framework import Program, program_guard

        @contextlib.contextmanager
        def _ctx():
            self._prog = Program()
            self._startup = Program()
            with program_guard(self._prog, self._startup):
                yield self
            if self._out_vars is None:
                raise ValueError(
                    "Preprocessor.block() ended without outputs(...)")
            # the transform may change arity/shape/dtype: the reader's
            # metadata must describe the TRANSFORMED batches, because
            # read_file declares its output vars from it
            self._state.shapes = [list(v.shape) if v.shape else [-1]
                                  for v in self._out_vars]
            self._state.dtypes = [str(v.dtype) for v in self._out_vars]
            self._state.lod_levels = (
                list(self._state.lod_levels[:len(self._out_vars)])
                + [0] * max(0, len(self._out_vars)
                            - len(self._state.lod_levels)))

        return _ctx()

    def inputs(self):
        from ..framework import default_main_program

        shapes = self._state.shapes
        dtypes = self._state.dtypes
        block = default_main_program().current_block()
        self._in_vars = []
        for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
            v = block.create_var(
                name=unique_name.generate("preprocessor_in"),
                shape=tuple(shape), dtype=dtype, is_data=True)
            self._in_vars.append(v)
        return self._in_vars

    def outputs(self, *outs):
        self._out_vars = list(outs)

    def __call__(self):
        from ..executor import Executor
        from .. import core as _core

        if self._out_vars is None:
            raise ValueError(
                "Preprocessor: define the transform inside `with "
                "pre.block():` before calling pre()")
        if getattr(self, "_applied", False):
            return self._reader  # idempotent: never double-transform
        self._applied = True
        exe = Executor(_core.CPUPlace())
        exe.run(self._startup)
        prog = self._prog
        in_names = [v.name for v in self._in_vars]
        out_names = [v.name for v in self._out_vars]
        inner_next = self._state.next_batch

        def transformed_next():
            from ..lod_tensor import LoDTensor

            batch = inner_next()  # [(arr, lod), ...]
            feed = {n: (LoDTensor(a, lod) if lod else a)
                    for n, (a, lod) in zip(in_names, batch)}
            outs = exe.run(prog, feed=feed, fetch_list=out_names,
                           return_numpy=False)
            # fetches are LoDTensors: lods survive pass-through slots
            return [(np.asarray(o), tuple(o.lod()) or None) for o in outs]

        self._state.next_batch = transformed_next
        return self._reader


class ParallelDo:
    """The reference's deprecated in-graph data parallelism
    (parallel_do_op.cc).  Redesigned away: use ParallelExecutor (GSPMD
    over the device mesh) — the same capability without per-place op
    replication (docs/OP_PARITY.md)."""

    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "ParallelDo was replaced by ParallelExecutor (GSPMD batch "
            "sharding over the mesh); see docs/OP_PARITY.md")

