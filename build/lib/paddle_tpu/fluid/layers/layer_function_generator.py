"""Layer-function generation utilities (ref:
python/paddle/fluid/layers/layer_function_generator.py — the reference
generates Python layer wrappers from C++ OpProtos; here the source of
truth is the op registry, so generate_layer_fn builds a wrapper from a
registered op's name)."""

from __future__ import annotations

import functools
import warnings

from ..layer_helper import LayerHelper

__all__ = ["generate_layer_fn", "autodoc", "templatedoc", "deprecated"]


def generate_layer_fn(op_type: str, input_slot: str = "X",
                      output_slot: str = "Out"):
    """Build a simple single-in/single-out layer for a registered op
    (ref :129 generate_layer_fn from OpProto)."""
    from ...ops.registry import is_registered

    if not is_registered(op_type):
        raise ValueError(f"op {op_type!r} is not registered")

    from .ops import _UNARY_ATTR_OPS, _UNARY_OPS

    shape_preserving = op_type in _UNARY_OPS or op_type in _UNARY_ATTR_OPS

    def layer(x, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        if shape_preserving:
            # only elementwise ops provably keep the input shape; other
            # ops leave the static shape unset rather than recording a
            # wrong one
            out.shape = tuple(x.shape)
        helper.append_op(type=op_type, inputs={input_slot: [x]},
                         outputs={output_slot: [out]}, attrs=attrs)
        return out

    layer.__name__ = op_type
    layer.__doc__ = f"Auto-generated wrapper for the `{op_type}` op."
    return layer


def autodoc(comment=""):
    """ref :221 — attach generated doc; the registry op docstring is the
    source here."""
    def deco(func):
        func.__doc__ = (comment + "\n" + (func.__doc__ or "")).strip()
        return func
    return deco


def templatedoc(op_type=None):
    """ref :247 — template docstring fill; no proto templates exist in
    this build, so the decorator is identity with the op name recorded."""
    def deco(func):
        if op_type and func.__doc__:
            func.__doc__ = func.__doc__.replace("${comment}", op_type)
        return func
    return deco


def deprecated(since="", instead=""):
    """Mark a layer deprecated; warns once per call site (ref
    annotations.deprecated)."""
    def deco(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(
                f"{func.__name__} is deprecated"
                + (f" since {since}" if since else "")
                + (f"; use {instead} instead" if instead else ""),
                DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)
        return wrapper
    return deco
