"""Weighted running average (ref: python/paddle/fluid/average.py —
WeightedAverage used by train loops to smooth per-batch metrics)."""

from __future__ import annotations

import numpy as np

__all__ = ["WeightedAverage"]


class WeightedAverage:
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = 0.0
        self.denominator = 0.0

    def add(self, value, weight=1.0):
        # elementwise accumulation, like the reference: arrays stay arrays
        self.numerator = self.numerator + np.asarray(value,
                                                     dtype=np.float64) \
            * weight
        self.denominator += weight

    def eval(self):
        if self.denominator == 0.0:
            raise ValueError(
                "WeightedAverage: there is no data to be averaged")
        out = self.numerator / self.denominator
        return float(out) if np.ndim(out) == 0 else out
