"""DataFeeder: minibatch list -> feed dict (ref: python/paddle/fluid/
data_feeder.py:83 — numpy conversion; LoD handling is host-side here)."""

from __future__ import annotations

import numpy as np

from . import core
from .framework import Variable, default_main_program

__all__ = ["DataFeeder"]


class DataToLoDTensorConverter:
    def __init__(self, place, lod_level, shape, dtype):
        self.place = place
        self.lod_level = lod_level
        self.shape = [d for d in shape]
        self.dtype = core.np_dtype(dtype)
        self.data = []
        self.lod = [[0] for _ in range(lod_level)]

    def feed(self, data):
        self._feed_impl_(data, self.lod, self.lod_level)

    def _feed_impl_(self, data, lod, lod_level):
        if lod_level == 0:
            self.data.append(data)
        else:
            lod[0].append(lod[0][-1] + len(data))
            for each_data in data:
                self._feed_impl_(each_data, lod[1:], lod_level - 1)

    def done(self):
        if self.lod_level == 0:
            arr = np.array(self.data, dtype=self.dtype)
            shape = [-1 if d in (-1, None) else d for d in self.shape]
            try:
                arr = arr.reshape(shape)
            except ValueError:
                pass
            return arr
        from .lod_tensor import LoDTensor

        flat = np.array(self.data, dtype=self.dtype)
        if flat.ndim == 1:
            flat = flat.reshape(
                [-1] + [d for d in self.shape if d not in (-1, None)])
        return LoDTensor(flat, self.lod)


class DataFeeder:
    def __init__(self, feed_list, place, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        program = program or default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block()._var_recursive(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("feed_list should contain Variables or names")
            self.feed_dtypes.append(each_var.dtype)
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(each_var.shape)
        self.place = place

    def feed(self, iterable):
        converters = [
            DataToLoDTensorConverter(self.place, lod_level, shape, dtype)
            for lod_level, shape, dtype in zip(
                self.feed_lod_level, self.feed_shapes, self.feed_dtypes)
        ]
        for each_sample in iterable:
            assert len(each_sample) == len(converters), \
                "sample width != number of feed variables"
            for each_converter, each_slot in zip(converters, each_sample):
                each_converter.feed(each_slot)
        return {name: conv.done()
                for name, conv in zip(self.feed_names, converters)}

    def feed_parallel(self, iterable, num_places=None):
        # ParallelExecutor accepts a merged global batch; just concatenate.
        from .lod_tensor import LoDTensor

        batches = [self.feed(batch) for batch in iterable]
        if len(batches) == 1:
            return batches[0]
        out = {}
        for k in batches[0]:
            vals = [b[k] for b in batches]
            if isinstance(vals[0], LoDTensor):
                data = np.concatenate([np.asarray(v) for v in vals], axis=0)
                lens = [v.recursive_sequence_lengths() for v in vals]
                merged = [sum((l[i] for l in lens), [])
                          for i in range(len(lens[0]))]
                t = LoDTensor(data)
                t.set_recursive_sequence_lengths(merged)
                out[k] = t
            else:
                out[k] = np.concatenate(vals, axis=0)
        return out
