"""Host-side LoDTensor and helpers (ref: python/paddle/fluid/lod_tensor.py,
paddle/fluid/framework/lod_tensor.h:58,110).

A LoDTensor is packed variable-length sequence data: sequences are
concatenated along dim 0 and a Level-of-Detail table of nested offsets
records the boundaries.  On TPU the offsets are *static metadata*: the
executor bakes them into the XLA trace as constants (see executor.py
trace_block), so device programs keep fully static shapes.

LoD forms:
 - "offsets" (the wire form, ref lod_tensor.h:58): ((0, 2, 5),) means two
   sequences, rows [0:2) and [2:5).
 - "recursive sequence lengths" (user-facing): [[2, 3]].
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "LoDTensor", "create_lod_tensor", "create_random_int_lodtensor",
]


def _lengths_to_offsets(lengths: Sequence[int]) -> Tuple[int, ...]:
    off = [0]
    for l in lengths:
        off.append(off[-1] + int(l))
    return tuple(off)


def _offsets_to_lengths(offsets: Sequence[int]) -> List[int]:
    return [int(offsets[i + 1]) - int(offsets[i])
            for i in range(len(offsets) - 1)]


def _normalize_lod(lod) -> Tuple[Tuple[int, ...], ...]:
    if not lod:
        return ()
    return tuple(tuple(int(x) for x in level) for level in lod)


def _is_device_array(a) -> bool:
    import jax

    return isinstance(a, jax.Array)


class LoDTensor:
    """Packed data + offset-form LoD.  Mirrors the pybind LoDTensor surface
    (ref: pybind/pybind.cc:160 — set/lod/set_lod/recursive_sequence_lengths)."""

    def __init__(self, data=None, lod=None):
        # device (jax) arrays are kept as-is and materialize lazily on
        # first numpy access — Executor.run(return_numpy=False) relies on
        # this to avoid a blocking D2H round-trip per step (the transport
        # behind a tunneled TPU charges ~100ms per forced fetch)
        if data is None or _is_device_array(data):
            self._data = data
        else:
            self._data = np.asarray(data)
        self._lod = _normalize_lod(lod)

    # numpy interop
    def __array__(self, dtype=None):
        a = self._data
        if a is None:
            raise ValueError("LoDTensor holds no data")
        if _is_device_array(a):
            a = self._data = np.asarray(a)
        return a.astype(dtype) if dtype is not None else a

    def set(self, array, place=None):
        self._data = np.asarray(array)

    @property
    def shape(self):
        return () if self._data is None else tuple(self._data.shape)

    def _dtype(self):
        return None if self._data is None else self._data.dtype

    # lod accessors
    def lod(self) -> Tuple[Tuple[int, ...], ...]:
        return self._lod

    def set_lod(self, lod):
        self._lod = _normalize_lod(lod)

    def recursive_sequence_lengths(self) -> List[List[int]]:
        return [_offsets_to_lengths(level) for level in self._lod]

    def set_recursive_sequence_lengths(self, lengths):
        self._lod = tuple(_lengths_to_offsets(l) for l in lengths)

    def has_valid_recursive_sequence_lengths(self) -> bool:
        if self._data is None:
            return False
        n = self._data.shape[0] if self._data.ndim else 0
        prev_count = None
        for level in self._lod:
            if not level or level[0] != 0 or list(level) != sorted(level):
                return False
            if prev_count is not None and len(level) - 1 != prev_count:
                return False
            prev_count = level[-1]
        if self._lod and self._lod[-1][-1] != n:
            return False
        return True

    def __repr__(self):  # pragma: no cover - cosmetic
        return f"LoDTensor(shape={self.shape}, lod={self._lod})"


def create_lod_tensor(data, recursive_seq_lens, place=None) -> LoDTensor:
    """ref: python/paddle/fluid/lod_tensor.py create_lod_tensor.

    ``data`` may be a numpy array (rows already packed), a list of lists
    (ragged; will be packed, trailing dim 1), or another LoDTensor (re-lod).
    """
    if isinstance(data, LoDTensor):
        t = LoDTensor(np.asarray(data))
        t.set_recursive_sequence_lengths(recursive_seq_lens)
        return t
    if isinstance(data, list):
        flat = []

        def _walk(x):
            if isinstance(x, (list, tuple)) and x \
                    and isinstance(x[0], (list, tuple)):
                for e in x:
                    _walk(e)
            else:
                flat.extend(x if isinstance(x, (list, tuple)) else [x])

        _walk(data)
        arr = np.asarray(flat).reshape(-1, 1)
    else:
        arr = np.asarray(data)
    t = LoDTensor(arr)
    t.set_recursive_sequence_lengths(recursive_seq_lens)
    if not t.has_valid_recursive_sequence_lengths():
        raise ValueError(
            f"invalid lod {recursive_seq_lens} for data with "
            f"{arr.shape[0]} rows")
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place,
                                low, high) -> LoDTensor:
    total = sum(recursive_seq_lens[-1])
    shape = [total] + list(base_shape)
    data = np.random.randint(low, high + 1, size=shape).astype("int64")
    return create_lod_tensor(data, recursive_seq_lens, place)
