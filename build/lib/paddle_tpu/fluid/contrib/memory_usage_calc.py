"""Estimate a Program's training memory footprint (ref:
python/paddle/fluid/contrib/memory_usage_calc.py — sums var sizes with a
batch-size substitution for the -1 dim and reports a low/high band).

On TPU the estimate approximates HBM residency of the jitted step:
parameters + optimizer accumulators persist; activations are bounded by
the per-var sum (XLA's actual liveness reuse makes the true peak lower, so
the band below brackets it the same way the reference's +-30% does)."""

from __future__ import annotations

import numpy as np

from ..framework import Program, default_main_program
from .. import core

DTYPE_TO_SIZE = {
    "float16": 2, "bfloat16": 2, "float32": 4, "float64": 8,
    "int8": 1, "uint8": 1, "int16": 2, "int32": 4, "int64": 8, "bool": 1,
}


def memory_usage(program: Program = None, batch_size: int = 1):
    """Returns (low_MB, high_MB) for one training step at batch_size."""
    program = program or default_main_program()
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")
    total = 0.0
    for var in program.list_vars():
        shape = var.shape
        if shape is None:
            continue
        dims = [batch_size if (s is None or int(s) < 0) else int(s)
                for s in shape]
        try:
            item = DTYPE_TO_SIZE[core.convert_dtype(var.dtype)]
        except (KeyError, ValueError):
            continue
        total += float(np.prod(dims)) * item if dims else item
    mb = total / (1024.0 ** 2)
    # the reference brackets its estimate at +-30%
    return mb * 0.7, mb * 1.3
