"""contrib utilities (ref: python/paddle/fluid/contrib/)."""

from . import decoder, memory_usage_calc
from .memory_usage_calc import memory_usage

__all__ = ["decoder", "memory_usage_calc", "memory_usage"]
