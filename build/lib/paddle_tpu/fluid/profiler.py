"""Profiler: host event aggregation + jax trace (ref:
python/paddle/fluid/profiler.py:39-221 and platform/profiler.cc — the
reference aggregates push/pop host events into sorted tables and captures
device activity via CUPTI; here host events come from the executor's
block/segment/op timers and device activity from ``jax.profiler``, whose
traces open in TensorBoard/perfetto/XProf).

``stop_profiler`` prints the reference-style aggregate table (calls, total,
min, max, ave) and writes a JSON event log that ``tools/timeline.py``
converts to a chrome://tracing file (ref: tools/timeline.py:36,115).
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import time

__all__ = ["cuda_profiler", "reset_profiler", "profiler", "start_profiler",
           "stop_profiler", "record_event", "is_profiling"]

_trace_dir = None
_on = False
_agg = {}        # name -> [calls, total, min, max]
_timeline = []   # {"name", "ts", "dur"} microseconds since start
_t0 = 0.0


def is_profiling() -> bool:
    return _on


def record_event(name: str, seconds: float, start: float = None) -> None:
    """Aggregate one timed host event (executor hooks call this)."""
    if not _on:
        return
    e = _agg.get(name)
    if e is None:
        _agg[name] = [1, seconds, seconds, seconds]
    else:
        e[0] += 1
        e[1] += seconds
        e[2] = min(e[2], seconds)
        e[3] = max(e[3], seconds)
    ts = ((start if start is not None else time.perf_counter() - seconds)
          - _t0) * 1e6
    _timeline.append({"name": name, "ts": ts, "dur": seconds * 1e6})


@contextlib.contextmanager
def _event(name):
    t = time.perf_counter()
    try:
        yield
    finally:
        record_event(name, time.perf_counter() - t, start=t)


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    # no CUDA on this stack; kept as a no-op shim for API parity
    yield


def reset_profiler():
    _agg.clear()
    _timeline.clear()


def start_profiler(state="All", trace_dir=None):
    global _trace_dir, _on, _t0
    import jax

    reset_profiler()
    _t0 = time.perf_counter()
    _on = True
    _trace_dir = trace_dir or os.path.join(tempfile.gettempdir(),
                                           "paddle_tpu_profile")
    try:
        jax.profiler.start_trace(_trace_dir)
    except RuntimeError:
        pass  # a trace may already be active


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    """Stop tracing, print the aggregate table, write the event log.

    sorted_key in {None, 'calls', 'total', 'max', 'min', 'ave'} mirrors the
    reference's EnableProfiler table ordering (platform/profiler.h:116)."""
    global _on
    import jax

    _on = False
    try:
        jax.profiler.stop_trace()
    except RuntimeError:
        pass

    rows = [(n, c, tot, mn, mx, tot / c)
            for n, (c, tot, mn, mx) in _agg.items()]
    key_idx = {"calls": 1, "total": 2, "min": 3, "max": 4, "ave": 5}
    rows.sort(key=lambda r: -r[key_idx.get(sorted_key, 2)])
    if rows:
        print(f"{'Event':<40} {'Calls':>8} {'Total(ms)':>12} "
              f"{'Min(ms)':>10} {'Max(ms)':>10} {'Ave(ms)':>10}")
        for n, c, tot, mn, mx, ave in rows:
            print(f"{n[:40]:<40} {c:>8} {tot * 1e3:>12.3f} "
                  f"{mn * 1e3:>10.3f} {mx * 1e3:>10.3f} {ave * 1e3:>10.3f}")
    if profile_path:
        with open(profile_path, "w") as f:
            json.dump({"events": _timeline, "trace_dir": _trace_dir}, f)
    return _trace_dir


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
