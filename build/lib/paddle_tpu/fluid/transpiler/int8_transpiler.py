"""Weight-only int8 inference transpiler.

The reference quantizes inference graphs through its analysis pipeline
(ref: inference/analysis/, fake_quantize/fake_dequantize ops, QAT flow);
the fp16 analogue is contrib/float16/float16_transpiler.py, which rewrites
weights in the scope and patches the program.  This is the TPU-native
int8 counterpart, specialized to the part that pays off under XLA:

 - weights of matmul/conv ops are stored int8 (4x less HBM, the real
   bottleneck on inference), with a per-output-channel abs-max scale;
 - a ``dequantize_weight`` op materializes the float weight right at the
   consuming op; XLA fuses the cast+scale into the matmul/conv read, so
   activations and accumulation stay float — "weight-only" quantization,
   the standard accuracy-safe recipe (<1%% drop without calibration data).

Scales come from the weights themselves (per-channel abs-max): weight-only
quantization needs no calibration data or QAT observers — the fake_quantize
ops (ops/quant_ops.py) remain the training-time QAT surface, and a QAT'd
model's weights quantize here losslessly since training already pinned them
to the quantization grid.
"""

from __future__ import annotations

import numpy as np

# op type -> (weight input slot, per-output-channel axis of the weight)
_QUANT_TARGETS = {
    "mul": ("Y", 1),        # [in, out]
    "conv2d": ("Filter", 0),  # [out_c, in_c, kh, kw]
}


class Int8WeightTranspiler:
    """Rewrite an INFERENCE program + scope for weight-only int8."""

    def __init__(self, min_elements: int = 64):
        # tiny weights (biases folded into mul, 1x1 vectors) aren't worth
        # the dequant op; skip anything smaller than min_elements
        self.min_elements = min_elements

    def transpile(self, program, place=None, scope=None):
        from ..executor import global_scope
        from ..framework import Parameter

        scope = scope or global_scope()
        gb = program.global_block()
        quantized = []
        for block in program.blocks:
            insertions = []  # (index, weight name, new input name)
            for i, op in enumerate(block.ops):
                target = _QUANT_TARGETS.get(op.type)
                if target is None:
                    continue
                slot, axis = target
                names = op.inputs.get(slot) or []
                if len(names) != 1:
                    continue
                wname = names[0]
                if not gb._has_var_recursive(wname) or \
                        not isinstance(gb._var_recursive(wname), Parameter):
                    continue
                w = scope.get(wname, None)
                if w is None:
                    continue
                w = np.asarray(w)
                if w.size < self.min_elements or \
                        not np.issubdtype(w.dtype, np.floating):
                    continue
                insertions.append((i, op, slot, axis, wname, w))
            # rewrite back-to-front so indices stay valid
            for i, op, slot, axis, wname, w in reversed(insertions):
                dq_name = self._quantize(block, scope, wname, w, axis)
                op.inputs[slot] = [dq_name]
                block._insert_op(
                    i, type="dequantize_weight",
                    inputs={"X": [wname + "@INT8"],
                            "Scale": [wname + "@SCALE"]},
                    outputs={"Out": [dq_name]},
                    attrs={"quant_axis": axis})
                quantized.append(wname)
        return quantized

    def _quantize(self, block, scope, wname, w, axis):
        """Store int8 weight + per-channel scale in scope/block; drop the
        float original from the scope (that is the memory win)."""
        gb = block.program.global_block()
        reduce_axes = tuple(d for d in range(w.ndim) if d != axis)
        scale = np.abs(w).max(axis=reduce_axes).astype(np.float32)
        scale = np.where(scale > 0, scale, 1.0)
        shape = [1] * w.ndim
        shape[axis] = -1
        q = np.clip(np.round(w / scale.reshape(shape) * 127.0),
                    -127, 127).astype(np.int8)

        wq_name, sc_name = wname + "@INT8", wname + "@SCALE"
        gb.create_var(name=wq_name, shape=tuple(q.shape), dtype="int8",
                      persistable=True)
        gb.create_var(name=sc_name, shape=tuple(scale.shape),
                      dtype="float32", persistable=True)
        dq_name = wname + "@DEQ"
        gb.create_var(name=dq_name, shape=tuple(w.shape), dtype="float32",
                      persistable=False)
        scope.set(wq_name, q)
        scope.set(sc_name, scale)
        scope._values.pop(wname, None)  # the float copy is the memory win
        return dq_name
