"""PS dispatchers (ref: transpiler/ps_dispatcher.py): assign variables to
"servers".  In the TPU build the pserver role is sharded state, so the
consumers are (a) the multihost sharded checkpoint, which round-robins
replicated variables across processes so every host writes a balanced
subset (parallel/multihost.py save_sharded — the pserver-shard layout of
ref go/pserver/service.go:346 applied to checkpoint IO), and (b) any
transpiler emulating a pserver var layout."""

from __future__ import annotations

import zlib


def _var_name(var) -> str:
    return var if isinstance(var, str) else var.name


class PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class HashName(PSDispatcher):
    def _hash_block(self, block_str, total):
        # crc32, NOT builtin hash(): str hash is salted per process
        # (PYTHONHASHSEED), and every process must agree on the layout
        return zlib.crc32(block_str.encode("utf-8")) % total

    def dispatch(self, varlist):
        eplist = []
        for var in varlist:
            server_id = self._hash_block(_var_name(var), len(self._eps))
            eplist.append(self._eps[server_id])
        return eplist


class RoundRobin(PSDispatcher):
    def dispatch(self, varlist):
        eplist = []
        for _ in varlist:
            eplist.append(self._eps[self._step])
            self._step = (self._step + 1) % len(self._eps)
        return eplist


def assign_writer(names, n_processes: int, kind: str = "round_robin"):
    """Deterministic {name: process_id} for replicated-var checkpoint
    writes.  Every process computes the identical map (names must arrive
    in the same order everywhere, which plan-derived state dicts do)."""
    d = (HashName if kind == "hash" else RoundRobin)(range(n_processes))
    return dict(zip(names, d.dispatch(list(names))))
