"""Memory-optimization transpiler (ref: transpiler/
memory_optimization_transpiler.py:47,381 — liveness-based var reuse).

On XLA this pass is a no-op by design: buffer liveness analysis and reuse
happen inside the compiler, and in-place parameter updates are expressed via
buffer donation in the Executor.  The API is preserved so reference training
scripts run unchanged.
"""

from __future__ import annotations


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0):
    if print_log:
        print("memory_optimize: no-op on XLA (compiler performs liveness reuse)")
    return input_program


def release_memory(input_program, skip_opt_set=None):
    return input_program
