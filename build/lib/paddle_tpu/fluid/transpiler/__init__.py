"""Transpilers (ref: python/paddle/fluid/transpiler/)."""

from .distribute_transpiler import DistributeTranspiler, DistributeTranspilerConfig
from .inference_transpiler import InferenceTranspiler
from .int8_transpiler import Int8WeightTranspiler
from .memory_optimization_transpiler import memory_optimize, release_memory
from .ps_dispatcher import HashName, RoundRobin

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "InferenceTranspiler", "Int8WeightTranspiler", "memory_optimize",
           "release_memory", "HashName", "RoundRobin"]
