"""Python-side streaming metrics (ref: python/paddle/fluid/metrics.py:53-423)."""

from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Accuracy", "Precision", "Recall",
           "ChunkEvaluator", "EditDistance", "Auc", "DetectionMAP"]


def _is_number_or_matrix(x):
    return isinstance(x, (int, float, np.ndarray)) or np.isscalar(x)


class MetricBase:
    def __init__(self, name):
        self._name = name or self.__class__.__name__

    def __str__(self):
        return self._name

    def reset(self):
        for attr, value in self.__dict__.items():
            if attr.startswith("_"):
                continue
            if isinstance(value, (int, float)):
                setattr(self, attr, 0)
            elif isinstance(value, (np.ndarray,)):
                setattr(self, attr, np.zeros_like(value))
            elif isinstance(value, list):
                setattr(self, attr, [])

    def update(self, preds, labels):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no batches accumulated")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall else 0.0


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).reshape(-1)[0])
        self.num_label_chunks += int(np.asarray(num_label_chunks).reshape(-1)[0])
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).reshape(-1)[0])

    def eval(self):
        precision = (float(self.num_correct_chunks) / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (float(self.num_correct_chunks) / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(distances != 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data accumulated")
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, -1] if preds.ndim == 2 else preds.reshape(-1)
        idx = np.clip((pos_prob * self._num_thresholds).astype(np.int64), 0,
                      self._num_thresholds)
        for i, l in zip(idx, labels):
            if l:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def eval(self):
        tot_pos = float(self._stat_pos.sum())
        tot_neg = float(self._stat_neg.sum())
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        prev_pos = np.concatenate([[0.0], pos_cum[:-1]])
        prev_neg = np.concatenate([[0.0], neg_cum[:-1]])
        area = float(np.sum((neg_cum - prev_neg) * (pos_cum + prev_pos) / 2.0))
        return area / (tot_pos * tot_neg)


class DetectionMAP(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.has_state = None

    def update(self, value, weight=None):
        self.has_state = value

    def eval(self):
        return self.has_state
