"""SelectedRows: the sparse-gradient representation (ref:
paddle/fluid/framework/selected_rows.h:32 — a row-index list plus a value
tensor of just those rows, produced by lookup_table's backward when
``is_sparse=True`` and consumed row-wise by sgd/adam and the pserver path).

TPU-native redesign: the reference's rows vector is dynamically sized (one
entry per *unique* id); XLA needs static shapes, so here SelectedRows keeps
one (row, value) pair per *occurrence* — shape [N] ids and [N, D] values for
a batch that looked up N ids.  Duplicates are legal (selected_rows.h allows
them too: "rows can be duplicated"); every consumer folds them with a
scatter-add, which is exactly a segment-sum on the MXU-adjacent VPU and
needs no host-side unique().  The structure is a jax pytree, so it flows
through jit/grad/GSPMD like any tensor pair.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["SelectedRows"]


@jax.tree_util.register_pytree_node_class
class SelectedRows:
    """rows: int array [N] (duplicates allowed); values: [N, ...] per-row
    payload; height: the dense dim-0 extent (vocab size) — static."""

    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height=None):
        self.rows = rows
        self.values = values
        self.height = int(height) if height is not None else None

    # -- pytree protocol --
    def tree_flatten(self):
        return (self.rows, self.values), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        rows, values = children
        return cls(rows, values, height)

    # -- consumers --
    def to_dense(self, height=None):
        """Fold into a dense [height, ...] tensor (scatter-add merges
        duplicate rows — ref: math/selected_rows_functor.cc MergeAdd)."""
        h = height if height is not None else self.height
        if h is None:
            raise ValueError("SelectedRows.to_dense needs a height")
        dense = jnp.zeros((h,) + tuple(self.values.shape[1:]),
                          self.values.dtype)
        return dense.at[self.rows].add(self.values)

    def scatter_sub_into(self, dense, scale=1.0):
        """dense - scale * this, applied only at the touched rows — the
        sparse optimizer update (ref: sgd_op.h SelectedRows branch)."""
        return dense.at[self.rows].add(-scale * self.values)

    def merge_with(self, other: "SelectedRows") -> "SelectedRows":
        """Sum of two sparse grads = concatenation (consumers scatter-add,
        so duplicate rows fold automatically; ref: sum over SelectedRows,
        math/selected_rows_functor.h Add)."""
        if not isinstance(other, SelectedRows):
            raise TypeError("can only merge SelectedRows with SelectedRows")
        return SelectedRows(
            jnp.concatenate([self.rows, other.rows], 0),
            jnp.concatenate([self.values, other.values], 0),
            self.height if self.height is not None else other.height)

    @property
    def shape(self):
        # advertise the dense shape so shape-probing heuristics stay sane
        if self.height is None:
            return tuple(self.values.shape)
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def __repr__(self):  # pragma: no cover - cosmetic
        return (f"SelectedRows(rows={tuple(self.rows.shape)}, "
                f"values={tuple(self.values.shape)}, height={self.height})")


def is_selected_rows(v) -> bool:
    return isinstance(v, SelectedRows)
