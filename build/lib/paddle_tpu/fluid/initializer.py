"""Parameter initializers (ref: python/paddle/fluid/initializer.py).

Each initializer appends an init op to the startup program's block; the
Executor materializes them as XLA computations with threefry randomness.
"""

from __future__ import annotations

import math

import numpy as np


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self._value = value

    def __call__(self, var, block):
        return block.append_op(
            type="fill_constant", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "value": float(self._value)})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self._low, self._high, self._seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type="uniform_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "min": self._low, "max": self._high, "seed": self._seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self._mean, "std": self._std, "seed": self._seed})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="truncated_gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self._mean, "std": self._std, "seed": self._seed})


def _fan_in_out(var):
    shape = var.shape
    if len(shape) < 2:
        return (shape[0] if shape else 1, shape[0] if shape else 1)
    receptive = 1
    for d in shape[2:]:
        receptive *= d
    # paddle convention: fc weight [in, out]; conv filter [out, in, k, k]
    if len(shape) == 2:
        return shape[0], shape[1]
    return shape[1] * receptive, shape[0] * receptive


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self._uniform, self._fan_in, self._fan_out, self._seed = \
            uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fin, fout = _fan_in_out(var)
        fin = self._fan_in if self._fan_in is not None else fin
        fout = self._fan_out if self._fan_out is not None else fout
        if self._uniform:
            limit = math.sqrt(6.0 / (fin + fout))
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = math.sqrt(2.0 / (fin + fout))
        return NormalInitializer(0.0, std, self._seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self._uniform, self._fan_in, self._seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fin, _ = _fan_in_out(var)
        fin = self._fan_in if self._fan_in is not None else fin
        if self._uniform:
            limit = math.sqrt(6.0 / fin)
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = math.sqrt(2.0 / fin)
        return NormalInitializer(0.0, std, self._seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsample filter init (ref: initializer.py Bilinear)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("Bilinear init needs a 4-D filter")
        weight = np.zeros(shape, dtype=np.float32)
        k = shape[3]
        f = int(np.ceil(k / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(np.prod(shape)):
            x = i % k
            y = (i // k) % shape[2]
            w = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            weight.flat[i] = w
        return NumpyArrayInitializer(weight)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self._value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            type="assign_value", outputs={"Out": var},
            attrs={"shape": list(self._value.shape), "dtype": var.dtype,
                   "fp32_values": [float(v) for v in self._value.flat]})


# API aliases matching the reference's public names
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def force_init_on_cpu():
    return False


def init_on_cpu():
    import contextlib

    @contextlib.contextmanager
    def _noop():
        yield

    return _noop()
