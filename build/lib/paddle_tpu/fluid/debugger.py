"""Program debugging / visualization (ref: python/paddle/fluid/debugger.py
— repr_var :98, pprint_program_codes :105, pprint_block_codes :114, and
graphviz.py's dot writer used by draw_block_graphviz).

Renders a Program as pseudo-code (one line per op: outs = op(ins) {attrs})
and emits GraphViz .dot for a block's op/var dataflow."""

from __future__ import annotations

from .framework import Program

__all__ = ["pprint_program_codes", "pprint_block_codes",
           "draw_block_graphviz"]


def _repr_var(var) -> str:
    shape = "x".join(str(s) for s in (var.shape or ()))
    return f"{var.name}[{var.dtype or '?'}:{shape}]"


def _repr_op(op) -> str:
    ins = ", ".join(f"{slot}={list(names)}"
                    for slot, names in sorted(op.inputs.items()) if names)
    outs = ", ".join(n for names in op.outputs.values() for n in names if n)
    keep = {k: v for k, v in op.attrs.items()
            if not k.startswith("__") and k != "op_role"}
    attrs = ", ".join(f"{k}={v!r}" for k, v in sorted(keep.items())
                      if not isinstance(v, (list, tuple)) or len(v) <= 6)
    s = f"{outs or '()'} = {op.type}({ins})"
    if attrs:
        s += " {" + attrs + "}"
    return s


def pprint_block_codes(block, show_vars=False) -> str:
    lines = [f"# block {block.idx} (parent {block.parent_idx})"]
    if show_vars:
        for name in sorted(block.vars):
            lines.append("  var  " + _repr_var(block.vars[name]))
    for op in block.ops:
        lines.append("  " + _repr_op(op))
    return "\n".join(lines)


def pprint_program_codes(program: Program, show_vars=False) -> str:
    out = []
    for block in program.blocks:
        out.append(pprint_block_codes(block, show_vars))
    text = "\n".join(out)
    print(text)
    return text


def draw_block_graphviz(block, path="block.dot", highlights=None) -> str:
    """Write a .dot graph: op nodes (boxes) wired through their in/out vars
    (ellipses).  Render with `dot -Tpng block.dot` (ref: debugger.py
    draw_block_graphviz + graphviz.py)."""
    highlights = set(highlights or [])

    def q(s):
        return '"' + str(s).replace('"', '\\"') + '"'

    lines = ["digraph G {", "  rankdir=TB;"]
    seen_vars = set()
    for i, op in enumerate(block.ops):
        op_id = f"op_{i}"
        color = "lightsalmon" if op.type in highlights else "lightblue"
        lines.append(f"  {op_id} [label={q(op.type)} shape=box "
                     f"style=filled fillcolor={color}];")
        for names in op.inputs.values():
            for n in names:
                if not n:
                    continue
                if n not in seen_vars:
                    seen_vars.add(n)
                    lines.append(f"  {q(n)} [shape=ellipse];")
                lines.append(f"  {q(n)} -> {op_id};")
        for names in op.outputs.values():
            for n in names:
                if not n:
                    continue
                if n not in seen_vars:
                    seen_vars.add(n)
                    lines.append(f"  {q(n)} [shape=ellipse];")
                lines.append(f"  {op_id} -> {q(n)};")
    lines.append("}")
    text = "\n".join(lines)
    with open(path, "w") as f:
        f.write(text)
    return path
