"""Dataset -> recordio conversion (ref: python/paddle/fluid/
recordio_writer.py — convert_reader_to_recordio_file; the chunk format
itself is the native component, paddle_tpu/native/recordio.cc)."""

from __future__ import annotations

import contextlib

import numpy as np

from ..native import RecordIOWriter
from ..native.tensor_pack import pack_batch
from .lod_tensor import LoDTensor

__all__ = ["convert_reader_to_recordio_file",
           "convert_reader_to_recordio_files"]


@contextlib.contextmanager
def create_recordio_writer(filename, compressor=1, max_num_records=None,
                           max_chunk_bytes=1 << 20):
    w = RecordIOWriter(filename, compressor, max_chunk_bytes)
    try:
        yield w
    finally:
        w.close()


def _feed_to_items(fed: dict, feed_order):
    items = []
    for name in feed_order:
        v = fed[name]
        if isinstance(v, LoDTensor):
            items.append((np.asarray(v), v.lod()))
        else:
            items.append((np.asarray(v), ()))
    return items


def convert_reader_to_recordio_file(filename, reader_creator, feeder,
                                    compressor=1, max_num_records=1000,
                                    feed_order=None):
    """Each sample from reader_creator becomes ONE record (packed tensor
    batch), matching the reference's per-sample record layout so the
    batch/shuffle reader decorators compose the same way."""
    feed_order = feed_order or feeder.feed_names
    counter = 0
    with create_recordio_writer(filename, compressor) as writer:
        for sample in reader_creator():
            fed = feeder.feed([sample])
            writer.write(pack_batch(_feed_to_items(fed, feed_order)))
            counter += 1
    return counter


def convert_reader_to_recordio_files(filename, batch_per_file,
                                     reader_creator, feeder, compressor=1,
                                     max_num_records=1000, feed_order=None):
    feed_order = feed_order or feeder.feed_names
    lines = []
    f_name, f_ext = filename.rsplit(".", 1) if "." in filename \
        else (filename, "recordio")
    batch = []
    part = 0

    def flush():
        nonlocal part
        if not batch:
            return
        path = f"{f_name}-{part:05d}.{f_ext}"
        with create_recordio_writer(path, compressor) as w:
            for rec in batch:
                w.write(rec)
        lines.append(path)
        batch.clear()
        part += 1

    for sample in reader_creator():
        fed = feeder.feed([sample])
        batch.append(pack_batch(_feed_to_items(fed, feed_order)))
        if len(batch) >= batch_per_file:
            flush()
    flush()
    return lines
