"""Composite nets (ref: python/paddle/fluid/nets.py — simple_img_conv_pool,
img_conv_group, sequence_conv_pool, glu, scaled_dot_product_attention)."""

from __future__ import annotations

from . import layers

__all__ = ["simple_img_conv_pool", "img_conv_group", "glu",
           "sequence_conv_pool",
           "scaled_dot_product_attention"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act, use_cudnn=use_cudnn)
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling, use_cudnn=use_cudnn)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def _ext(v):
        if not hasattr(v, "__len__"):
            return [v] * len(conv_num_filter)
        return list(v)

    conv_padding = _ext(conv_padding)
    conv_filter_size = _ext(conv_filter_size)
    param_attr = _ext(param_attr)
    conv_with_batchnorm = _ext(conv_with_batchnorm)
    conv_batchnorm_drop_rate = _ext(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(
            input=tmp, num_filters=conv_num_filter[i],
            filter_size=conv_filter_size[i], padding=conv_padding[i],
            param_attr=param_attr[i], act=local_conv_act, use_cudnn=use_cudnn)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rate)
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride,
                         use_cudnn=use_cudnn)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    from .layers import ops as _ops

    return layers.elementwise_mul(a, _ops.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled dot-product attention (ref: nets.py).  On TPU this
    traces into batched MXU matmuls; flash/ring variants live in
    paddle_tpu.parallel."""
    if len(queries.shape) != 3 or len(keys.shape) != 3 or len(values.shape) != 3:
        raise ValueError("inputs must be 3-D [batch, seq, dim]")

    def _split_heads(x, n):
        if n == 1:
            return x
        hidden = x.shape[-1]
        reshaped = layers.reshape(
            x, shape=[x.shape[0] if x.shape[0] not in (-1, None) else -1,
                      x.shape[1], n, hidden // n])
        return layers.transpose(reshaped, perm=[0, 2, 1, 3])

    def _combine_heads(x):
        if len(x.shape) == 3:
            return x
        t = layers.transpose(x, perm=[0, 2, 1, 3])
        return layers.reshape(
            t, shape=[t.shape[0] if t.shape[0] not in (-1, None) else -1,
                      t.shape[1], t.shape[2] * t.shape[3]])

    q = _split_heads(queries, num_heads)
    k = _split_heads(keys, num_heads)
    v = _split_heads(values, num_heads)
    key_dim = float(queries.shape[-1] // num_heads)
    scaled_q = layers.scale(q, scale=key_dim ** -0.5)
    product = layers.matmul(scaled_q, k, transpose_y=True)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    ctx_multiheads = layers.matmul(weights, v)
    return _combine_heads(ctx_multiheads)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max"):
    """sequence_conv + sequence_pool (ref: nets.py sequence_conv_pool —
    the text-CNN building block the sentiment/book chapters use)."""
    conv_out = layers.sequence_conv(input=input, num_filters=num_filters,
                                    filter_size=filter_size,
                                    param_attr=param_attr, act=act)
    return layers.sequence_pool(input=conv_out, pool_type=pool_type)
