"""Structured losses: linear-chain CRF, CTC (warpctc), NCE, hierarchical
sigmoid, edit distance, chunk evaluation, ctc alignment.

ref: paddle/fluid/operators/{linear_chain_crf,crf_decoding,warpctc,nce,
hierarchical_sigmoid,edit_distance,chunk_eval,ctc_align}_op.*.

TPU design: the dynamic programs (CRF forward, Viterbi, CTC alpha) run as
``lax.scan`` over padded [num_seq, T, ...] batches built from static lod —
log-space throughout (the reference works in exp space with row-max
rescaling, operators/math/cross_entropy + linear_chain_crf_op.h; log-space
is the numerically-equivalent XLA-friendly form).  Gradients fall out of
jax.vjp over the scans.  chunk_eval / ctc_align produce data-dependent
shapes/contents and run on the eager tier.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, register_grad
from .array_ops import EAGER_OPS
from .rnn_ops import _pad_indices, _to_padded

EAGER_OPS.update({"chunk_eval", "ctc_align", "edit_distance"})

NEG = -1e30


def _padded_batch(x, off, reverse=False):
    """packed [N, ...] + offsets -> ([S, T, ...], mask [S, T], lens)."""
    idx, inv, mask, n, t_max = _pad_indices(off, reverse)
    return _to_padded(x, idx), jnp.asarray(mask), inv, n, t_max


def _to_packed_rows(padded, inv):
    """[S, T, ...] -> packed [N, ...] via the inverse index map."""
    s, t = padded.shape[0], padded.shape[1]
    flat = padded.reshape((s * t,) + padded.shape[2:])
    return flat[jnp.asarray(inv)]


# ---------------------------------------------------------------------------
# linear-chain CRF
# ---------------------------------------------------------------------------


@register_op("linear_chain_crf", no_grad_inputs=("Label",))
def linear_chain_crf(ctx):
    """ref: linear_chain_crf_op.cc — Transition rows: [start; end; A].

    Outputs LogLikelihood = NEGATIVE log-likelihood per sequence (the
    quantity the reference's book models minimize directly)."""
    emission = ctx.input("Emission")       # [N, K] packed
    transition = ctx.input("Transition")   # [K+2, K]
    label = ctx.input("Label")             # [N, 1] int
    off = np.asarray(ctx.seq_offsets("Emission"))
    k = emission.shape[1]
    start_w, end_w, trans = transition[0], transition[1], transition[2:]

    em, mask, inv, n_seq, t_max = _padded_batch(emission, off)
    lab = _to_padded(label.reshape(-1), _pad_indices(off)[0]).astype(jnp.int32)
    mask_f = mask.astype(em.dtype)

    # forward algorithm (log space), scan over time
    alpha0 = start_w[None, :] + em[:, 0, :]

    def fwd(alpha, t):
        em_t = em[:, t, :]
        m_t = mask_f[:, t][:, None]
        nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None, :, :], axis=1)
        alpha_new = em_t + nxt
        return alpha * (1 - m_t) + alpha_new * m_t, alpha_new

    alpha_fin, alphas = lax.scan(fwd, alpha0, jnp.arange(1, max(t_max, 1)))
    log_z = jax.nn.logsumexp(alpha_fin + end_w[None, :], axis=1)

    # gold path score
    lens = np.asarray(off[1:] - off[:-1])
    first_lab = lab[:, 0]
    last_idx = jnp.asarray(np.maximum(lens - 1, 0))
    last_lab = jnp.take_along_axis(lab, last_idx[:, None], axis=1)[:, 0]
    em_score = jnp.sum(
        jnp.take_along_axis(em, lab[:, :, None], axis=2)[:, :, 0] * mask_f,
        axis=1)
    pair_mask = mask_f[:, 1:]
    tr_score = jnp.sum(trans[lab[:, :-1], lab[:, 1:]] * pair_mask, axis=1) \
        if t_max > 1 else 0.0
    gold = start_w[first_lab] + em_score + tr_score + end_w[last_lab]

    nll = (log_z - gold).reshape(-1, 1)
    res = {"LogLikelihood": nll, "LogLikelihood@LOD": [None]}
    if ctx.n_outputs("Alpha"):
        # real (log-space) forward variables, repacked to lod rows
        all_alpha = jnp.concatenate([alpha0[:, None, :],
                                     jnp.transpose(alphas, (1, 0, 2))],
                                    axis=1) if t_max > 1 \
            else alpha0[:, None, :]
        res["Alpha"] = _to_packed_rows(all_alpha, inv)
    if ctx.n_outputs("EmissionExps"):
        res["EmissionExps"] = jnp.exp(emission)
    if ctx.n_outputs("TransitionExps"):
        res["TransitionExps"] = jnp.exp(transition)
    return res


@register_op("crf_decoding", no_grad_inputs=("Emission", "Transition",
                                             "Label"))
def crf_decoding(ctx):
    """ref: crf_decoding_op.cc — Viterbi; with Label, emit per-position
    correctness 0/1 (the chunk_eval co-input)."""
    emission = ctx.input("Emission")
    transition = ctx.input("Transition")
    label = ctx.input("Label")
    off = np.asarray(ctx.seq_offsets("Emission"))
    start_w, end_w, trans = transition[0], transition[1], transition[2:]

    em, mask, inv, n_seq, t_max = _padded_batch(emission, off)
    mask_f = mask.astype(em.dtype)

    alpha0 = start_w[None, :] + em[:, 0, :]

    def step(alpha, t):
        m_t = mask_f[:, t][:, None]
        cand = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(cand, axis=1)
        alpha_new = em[:, t, :] + jnp.max(cand, axis=1)
        return alpha * (1 - m_t) + alpha_new * m_t, best_prev

    alpha_fin, back = lax.scan(step, alpha0, jnp.arange(1, max(t_max, 1)))

    # backtrack as a reverse scan: positions past each sequence's end are
    # mask-gated, so cur holds that sequence's own best-last tag until its
    # true final step is reached
    best_last = jnp.argmax(alpha_fin + end_w[None, :], axis=1).astype(
        jnp.int32)

    def bt(cur, t):
        ptr = back[t - 1]                                     # [S, K]
        prev = jnp.take_along_axis(ptr, cur[:, None],
                                   axis=1)[:, 0].astype(jnp.int32)
        cur2 = jnp.where(mask[:, t], prev, cur)
        return cur2, cur                                      # emit tag@t

    if t_max > 1:
        cur0, tags_rev = lax.scan(bt, best_last,
                                  jnp.arange(t_max - 1, 0, -1))
        # tags_rev[i] = tag at position t_max-1-i; prepend position 0
        padded_path = jnp.concatenate(
            [cur0[:, None], jnp.flip(jnp.transpose(tags_rev), axis=1)],
            axis=1)                                           # [S, T]
    else:
        padded_path = best_last[:, None]
    viterbi = _to_packed_rows(padded_path, inv).reshape(-1, 1).astype(
        jnp.int64)
    if label is not None:
        correct = (viterbi == label.astype(viterbi.dtype)).astype(jnp.int64)
        return {"ViterbiPath": correct}
    return {"ViterbiPath": viterbi}


# ---------------------------------------------------------------------------
# CTC (warpctc)
# ---------------------------------------------------------------------------


@register_op("warpctc", no_grad_inputs=("Label",))
def warpctc(ctx):
    """ref: warpctc_op.cc — CTC loss on packed (lod) logits/labels.

    Log-space alpha recursion over the blank-interleaved label l'
    (standard CTC forward), scanned over time for the whole padded batch.
    """
    logits = ctx.input("Logits")           # [N, C] packed, unnormalized
    label = ctx.input("Label")             # [L, 1] packed int
    blank = int(ctx.attr("blank", 0))
    norm_by_times = bool(ctx.attr("norm_by_times", False))
    log_off = np.asarray(ctx.seq_offsets("Logits"))
    lab_off = np.asarray(ctx.seq_offsets("Label"))

    t_lens = np.asarray(log_off[1:] - log_off[:-1])
    l_lens = np.asarray(lab_off[1:] - lab_off[:-1])
    # the reference kernel errors on infeasible pairs; lengths are static
    # here so catch what we can at trace time (repeats need label values)
    for i in range(len(t_lens)):
        if t_lens[i] < l_lens[i]:
            raise ValueError(
                f"warpctc: sequence {i} has {int(t_lens[i])} frames but "
                f"{int(l_lens[i])} labels — no CTC alignment exists")
    l_max = int(l_lens.max()) if len(l_lens) else 0

    def _loss_fn(lg):
        log_probs = jax.nn.log_softmax(lg, axis=-1)
        lp, mask, inv, n_seq, t_max = _padded_batch(log_probs, log_off)

        lab_idx, _, lab_mask, _, _ = _pad_indices(lab_off)
        lab = _to_padded(label.reshape(-1), lab_idx).astype(jnp.int32)

        # l' = [blank, y1, blank, y2, ..., blank], length 2*l_max+1
        s_len = 2 * l_max + 1
        ext = jnp.full((n_seq, s_len), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        ext_valid = np.zeros((n_seq, s_len), bool)
        for i in range(n_seq):
            ext_valid[i, : 2 * int(l_lens[i]) + 1] = True
        ext_valid = jnp.asarray(ext_valid)

        # can-skip: l'[s] != blank and l'[s] != l'[s-2]
        skip_ok = jnp.zeros((n_seq, s_len), bool)
        if s_len > 2:
            skip_ok = skip_ok.at[:, 2:].set(
                (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2]))

        def emit(t):
            return jnp.take_along_axis(lp[:, t, :], ext, axis=1)

        alpha = jnp.full((n_seq, s_len), NEG, lp.dtype)
        alpha = alpha.at[:, 0].set(emit(0)[:, 0])
        if s_len > 1:
            alpha = alpha.at[:, 1].set(
                jnp.where(ext_valid[:, 1], emit(0)[:, 1], NEG))

        def step(alpha, t):
            stay = alpha
            prev1 = jnp.concatenate(
                [jnp.full((n_seq, 1), NEG, alpha.dtype), alpha[:, :-1]],
                axis=1)
            prev2 = jnp.concatenate(
                [jnp.full((n_seq, 2), NEG, alpha.dtype), alpha[:, :-2]],
                axis=1)
            prev2 = jnp.where(skip_ok, prev2, NEG)
            merged = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2)
            new = merged + emit(t)
            new = jnp.where(ext_valid, new, NEG)
            m_t = jnp.asarray(mask[:, t])[:, None]
            return jnp.where(m_t, new, alpha), None

        alpha, _ = lax.scan(step, alpha, jnp.arange(1, max(t_max, 1)))

        # loss = -log(alpha[2L] + alpha[2L-1]) at the last frame
        last_s = jnp.asarray(2 * l_lens)
        a_end = jnp.take_along_axis(alpha, last_s[:, None], axis=1)[:, 0]
        a_end1 = jnp.take_along_axis(
            alpha, jnp.maximum(last_s - 1, 0)[:, None], axis=1)[:, 0]
        ll = jnp.logaddexp(a_end, jnp.where(jnp.asarray(l_lens) > 0,
                                            a_end1, NEG))
        loss = -ll
        if norm_by_times:
            loss = loss / jnp.asarray(t_lens, loss.dtype)
        return loss.reshape(-1, 1).astype(lg.dtype)

    loss, vjp_fn = jax.vjp(_loss_fn, logits)
    res = {"Loss": loss, "Loss@LOD": [None]}
    if ctx.n_outputs("WarpCTCGrad"):
        # d(sum loss)/d logits — the reference's cached backward buffer;
        # XLA dead-code-eliminates this when the output is unused
        (res["WarpCTCGrad"],) = vjp_fn(jnp.ones_like(loss))
    return res


# ---------------------------------------------------------------------------
# NCE / hierarchical sigmoid
# ---------------------------------------------------------------------------


def _nce_cost(x, weight, bias, label, samples, k, num_classes):
    """Shared NCE objective given fixed noise samples."""
    num_true = label.shape[1]

    def logits_for(ids):
        w = weight[ids]                    # [B, n, D]
        out = jnp.einsum("bd,bnd->bn", x, w)
        if bias is not None:
            out = out + bias.reshape(-1)[ids]
        return out

    log_kq = jnp.log(float(k) / num_classes)
    true_lg = logits_for(label) - log_kq
    noise_lg = logits_for(samples) - log_kq
    cost = jnp.sum(jax.nn.softplus(-true_lg), axis=1) / num_true \
        + jnp.sum(jax.nn.softplus(noise_lg), axis=1)
    return cost, true_lg, noise_lg


@register_op("nce", no_grad_inputs=("Label", "SampleWeight"),
             stateful=True)
def nce(ctx):
    """ref: nce_op.cc — noise-contrastive estimation, uniform sampler.
    Fresh negatives each step from the threaded rng; the grad op replays
    the objective with the SampleLabels the forward actually drew."""
    x = ctx.input("Input")                 # [B, D]
    label = ctx.input("Label")             # [B, num_true]
    weight = ctx.input("Weight")           # [C, D]
    bias = ctx.input("Bias")               # [C]
    num_classes = int(ctx.attr("num_total_classes"))
    k = int(ctx.attr("num_neg_samples", 10))
    b = x.shape[0]
    num_true = label.shape[1] if label.ndim > 1 else 1
    label = label.reshape(b, num_true)

    # Determinism tiers (ref nce_op.h PrepareSamples): custom_neg_classes
    # pins the negatives outright (the reference's unit-test hook); a
    # nonzero seed attr gives one fixed PRNGKey-derived sample matrix
    # (reproducible across runs/sessions); else fresh draws from the
    # session-threaded rng each step.
    custom = ctx.attr("custom_neg_classes") or []
    seed = int(ctx.attr("seed", 0))
    if custom:
        samples = jnp.broadcast_to(
            jnp.asarray(np.asarray(custom, np.int64)[None, :]), (b, len(custom)))
        k = len(custom)
    else:
        key = jax.random.PRNGKey(seed) if seed != 0 else ctx.rng()
        samples = jax.random.randint(key, (b, k), 0, num_classes)
    cost, true_lg, noise_lg = _nce_cost(x, weight, bias, label, samples,
                                        k, num_classes)
    return {"Cost": cost.reshape(-1, 1),
            "SampleLogits": jnp.concatenate([true_lg, noise_lg], axis=1),
            "SampleLabels": jnp.concatenate([label, samples], axis=1)}


@register_grad("nce")
def nce_grad(ctx):
    """Differentiates _nce_cost with the forward's drawn samples (read
    back from the SampleLabels output)."""
    x = ctx.input("Input")
    label = ctx.input("Label")
    weight = ctx.input("Weight")
    bias = ctx.input("Bias")
    sample_labels = ctx.input("SampleLabels")
    gcost = ctx.input("Cost@GRAD")
    num_classes = int(ctx.attr("num_total_classes"))
    b = x.shape[0]
    num_true = label.shape[1] if label.ndim > 1 else 1
    label = label.reshape(b, num_true)
    samples = sample_labels[:, num_true:]
    k = samples.shape[1]  # actual draw count (custom_neg_classes may differ)

    cot = gcost.reshape(-1).astype(x.dtype)
    if bias is not None:
        _, vjp_fn = jax.vjp(
            lambda xv, wv, bv: _nce_cost(xv, wv, bv, label, samples, k,
                                         num_classes)[0], x, weight, bias)
        gx, gw, gb = vjp_fn(cot)
        return {"Input@GRAD": gx, "Weight@GRAD": gw, "Bias@GRAD": gb}
    _, vjp_fn = jax.vjp(
        lambda xv, wv: _nce_cost(xv, wv, None, label, samples, k,
                                 num_classes)[0], x, weight)
    gx, gw = vjp_fn(cot)
    return {"Input@GRAD": gx, "Weight@GRAD": gw}


@register_op("hierarchical_sigmoid", no_grad_inputs=("Label",))
def hierarchical_sigmoid(ctx):
    """ref: hierarchical_sigmoid_op.cc + math/matrix_bit_code.h — complete
    binary tree over classes; code(c) = c + num_classes, path node ids
    code>>(d+1) - 1, bit (code>>d)&1."""
    x = ctx.input("X")                     # [B, D]
    w = ctx.input("W")                     # [C-1, D]
    label = ctx.input("Label").reshape(-1)  # [B]
    bias = ctx.input("Bias")               # [1, C-1] or [C-1]
    num_classes = int(ctx.attr("num_classes"))
    code = label.astype(jnp.int32) + num_classes
    max_depth = int(np.floor(np.log2(num_classes))) + 1

    total = 0.0
    pre_out = []
    for d in range(max_depth):
        node = (code >> (d + 1)) - 1
        valid = node >= 0
        bit = (code >> d) & 1
        node_c = jnp.maximum(node, 0)
        logit = jnp.einsum("bd,bd->b", x, w[node_c])
        if bias is not None:
            logit = logit + bias.reshape(-1)[node_c]
        # sigmoid cross entropy with target = bit
        loss_d = jax.nn.softplus(logit) - bit * logit
        total = total + jnp.where(valid, loss_d, 0.0)
        pre_out.append(jnp.where(valid, logit, 0.0))
    res = {"Out": total.reshape(-1, 1)}
    if ctx.n_outputs("PreOut"):
        res["PreOut"] = jnp.stack(pre_out, axis=1)
    return res


# ---------------------------------------------------------------------------
# edit distance / chunk eval / ctc align (metrics; eager tier)
# ---------------------------------------------------------------------------


@register_op("edit_distance", no_grad_inputs=("Hyps", "Refs"))
def edit_distance(ctx):
    """ref: edit_distance_op.cc — Levenshtein per (hyp, ref) pair."""
    hyps = np.asarray(ctx.input("Hyps")).reshape(-1)
    refs = np.asarray(ctx.input("Refs")).reshape(-1)
    h_off = np.asarray(ctx.seq_offsets("Hyps"))
    r_off = np.asarray(ctx.seq_offsets("Refs"))
    normalized = bool(ctx.attr("normalized", False))
    n = len(h_off) - 1
    out = np.zeros((n, 1), np.float32)
    for i in range(n):
        h = hyps[h_off[i]: h_off[i + 1]]
        r = refs[r_off[i]: r_off[i + 1]]
        m, l = len(h), len(r)
        dp = np.arange(l + 1, dtype=np.int64)
        for a in range(1, m + 1):
            prev = dp.copy()
            dp[0] = a
            for bi in range(1, l + 1):
                dp[bi] = min(prev[bi] + 1, dp[bi - 1] + 1,
                             prev[bi - 1] + (h[a - 1] != r[bi - 1]))
        d = float(dp[l])
        if normalized:
            d = d / max(l, 1)
        out[i, 0] = d
    return {"Out": jnp.asarray(out),
            "SequenceNum": jnp.asarray([n], jnp.int64)}


def _extract_chunks(tags, scheme, num_types):
    """(type, begin, end) chunks from a tag sequence (IOB/IOE/IOBES/plain).

    Tag layout per ref chunk_eval_op.h: scheme 'IOB' -> tag = type*2 +
    {0:B, 1:I}; 'IOE' -> {0:I, 1:E}; 'IOBES' -> type*4 + {B,I,E,S};
    'plain' -> tag = type.  The 'other' tag is num_types*k (the largest).
    """
    chunks = []
    cur_type, cur_start = None, None

    def flush(end):
        nonlocal cur_type, cur_start
        if cur_type is not None:
            chunks.append((cur_type, cur_start, end))
            cur_type, cur_start = None, None

    for i, t in enumerate(tags):
        t = int(t)
        if scheme == "plain":
            ty = t if t < num_types else None
            if ty is None:
                flush(i)
            elif cur_type != ty:
                flush(i)
                cur_type, cur_start = ty, i
            continue
        if scheme == "IOB":
            n_tag = 2
            ty, pos = divmod(t, n_tag) if t < num_types * n_tag else (None, None)
            if ty is None:
                flush(i)
            elif pos == 0:          # B
                flush(i)
                cur_type, cur_start = ty, i
            else:                   # I
                if cur_type != ty:
                    flush(i)
                    cur_type, cur_start = ty, i
        elif scheme == "IOE":
            n_tag = 2
            ty, pos = divmod(t, n_tag) if t < num_types * n_tag else (None, None)
            if ty is None:
                flush(i)
            else:
                if cur_type != ty:
                    flush(i)
                    cur_type, cur_start = ty, i
                if pos == 1:        # E closes the chunk
                    flush(i + 1)
        elif scheme == "IOBES":
            n_tag = 4
            ty, pos = divmod(t, n_tag) if t < num_types * n_tag else (None, None)
            if ty is None:
                flush(i)
            elif pos == 0:          # B
                flush(i)
                cur_type, cur_start = ty, i
            elif pos == 1:          # I
                if cur_type != ty:
                    flush(i)
                    cur_type, cur_start = ty, i
            elif pos == 2:          # E
                if cur_type != ty:
                    cur_type, cur_start = ty, i
                flush(i + 1)
            else:                   # S
                flush(i)
                chunks.append((ty, i, i + 1))
    flush(len(tags))
    return set(chunks)


@register_op("chunk_eval", no_grad_inputs=("Inference", "Label"))
def chunk_eval(ctx):
    """ref: chunk_eval_op.cc — precision/recall/F1 over extracted chunks."""
    inf = np.asarray(ctx.input("Inference")).reshape(-1)
    lab = np.asarray(ctx.input("Label")).reshape(-1)
    off = np.asarray(ctx.seq_offsets("Inference"))
    num_types = int(ctx.attr("num_chunk_types"))
    scheme = str(ctx.attr("chunk_scheme", "IOB"))
    excluded = set(ctx.attr("excluded_chunk_types") or [])

    n_inf = n_lab = n_correct = 0
    for i in range(len(off) - 1):
        seq_inf = inf[off[i]: off[i + 1]]
        seq_lab = lab[off[i]: off[i + 1]]
        ci = {c for c in _extract_chunks(seq_inf, scheme, num_types)
              if c[0] not in excluded}
        cl = {c for c in _extract_chunks(seq_lab, scheme, num_types)
              if c[0] not in excluded}
        n_inf += len(ci)
        n_lab += len(cl)
        n_correct += len(ci & cl)
    p = n_correct / n_inf if n_inf else 0.0
    r = n_correct / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    return {
        "Precision": jnp.asarray([p], jnp.float32),
        "Recall": jnp.asarray([r], jnp.float32),
        "F1-Score": jnp.asarray([f1], jnp.float32),
        # int64 parity with the reference (chunk_eval_op.h outputs int64);
        # host numpy arrays sidestep jax's disabled-x64 truncation — this is
        # an eager metric op, nothing downstream re-enters jit with these.
        "NumInferChunks": np.asarray([n_inf], np.int64),
        "NumLabelChunks": np.asarray([n_lab], np.int64),
        "NumCorrectChunks": np.asarray([n_correct], np.int64),
    }


@register_op("ctc_align", no_grad_inputs=("Input",))
def ctc_align(ctx):
    """ref: ctc_align_op.cc — merge repeats, drop blanks (eager: output
    packing is data-dependent)."""
    x = np.asarray(ctx.input("Input")).reshape(-1)
    off = np.asarray(ctx.seq_offsets("Input"))
    blank = int(ctx.attr("blank", 0))
    merge = bool(ctx.attr("merge_repeated", True))
    rows, lens = [], []
    for i in range(len(off) - 1):
        seq = x[off[i]: off[i + 1]]
        out = []
        prev = None
        for t in seq:
            t = int(t)
            if merge and prev is not None and t == prev:
                prev = t
                continue
            prev = t
            if t != blank:
                out.append(t)
        rows.extend(out)
        lens.append(len(out))
    offsets = tuple(np.concatenate([[0], np.cumsum(lens)]).tolist())
    arr = jnp.asarray(np.asarray(rows, np.int64).reshape(-1, 1)) if rows \
        else jnp.zeros((0, 1), jnp.int64)
    return {"Output": arr, "Output@LOD": [(offsets,)]}
