"""Detection op family (ref: paddle/fluid/operators/detection/ —
prior_box_op.h, box_coder_op.h, iou_similarity_op.h, bipartite_match_op.cc,
target_assign_op.h, multiclass_nms_op.cc, roi_pool_op.*, and
polygon_box_transform_op.cc, anchor_generator_op.h).

TPU design notes:
 - prior/anchor generation is attr-static: the per-prior (w, h) table is
   built on host at trace time, only the center grid is device math.
 - bipartite_match is a greedy global-argmax loop; the reference pins it to
   CPU (bipartite_match_op.cc GetExpectedKernelType), here it is a
   ``lax.fori_loop`` over rows with masked argmax — stays inside the jitted
   program, no host round-trip.
 - multiclass_nms produces a data-dependent number of boxes (LoD output),
   which no static-shape program can express — it runs as an EAGER host op
   (the executor's two-tier fallback), matching its role as a CPU
   postprocessing op in the reference.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op

NO_GRAD = object()


# ---------------------------------------------------------------------------
# prior_box / anchor generation
# ---------------------------------------------------------------------------


def _expand_aspect_ratios(ratios, flip):
    out = [1.0]
    for ar in ratios or []:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(float(ar))
        if flip:
            out.append(1.0 / float(ar))
    return out


def _prior_whs(min_sizes, max_sizes, aspect_ratios, min_max_order):
    """Host-side per-prior (half_w, half_h) table (ref prior_box_op.h:104+:
    the ordering differs under min_max_aspect_ratios_order)."""
    whs = []
    for s, mn in enumerate(min_sizes):
        if min_max_order:
            whs.append((mn / 2.0, mn / 2.0))
            if max_sizes:
                m = math.sqrt(mn * max_sizes[s]) / 2.0
                whs.append((m, m))
            for ar in aspect_ratios:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((mn * math.sqrt(ar) / 2.0,
                            mn / math.sqrt(ar) / 2.0))
        else:
            for ar in aspect_ratios:
                whs.append((mn * math.sqrt(ar) / 2.0,
                            mn / math.sqrt(ar) / 2.0))
            if max_sizes:
                m = math.sqrt(mn * max_sizes[s]) / 2.0
                whs.append((m, m))
    return whs


@register_op("prior_box", no_grad_inputs=("Input", "Image"))
def prior_box(ctx):
    feat, image = ctx.input("Input"), ctx.input("Image")
    min_sizes = [float(v) for v in ctx.attr("min_sizes")]
    max_sizes = [float(v) for v in (ctx.attr("max_sizes") or [])]
    aspect_ratios = _expand_aspect_ratios(ctx.attr("aspect_ratios") or [],
                                          ctx.attr("flip", False))
    variances = [float(v) for v in ctx.attr("variances") or
                 [0.1, 0.1, 0.2, 0.2]]
    clip = ctx.attr("clip", False)
    offset = ctx.attr("offset", 0.5)
    img_h, img_w = image.shape[2], image.shape[3]
    fh, fw = feat.shape[2], feat.shape[3]
    step_w = ctx.attr("step_w", 0.0) or img_w / fw
    step_h = ctx.attr("step_h", 0.0) or img_h / fh
    whs = _prior_whs(min_sizes, max_sizes, aspect_ratios,
                     ctx.attr("min_max_aspect_ratios_order", False))

    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w  # [fw]
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h  # [fh]
    half = jnp.asarray(whs, jnp.float32)  # [P, 2] (half_w, half_h)
    cxg = jnp.broadcast_to(cx[None, :, None], (fh, fw, half.shape[0]))
    cyg = jnp.broadcast_to(cy[:, None, None], (fh, fw, half.shape[0]))
    boxes = jnp.stack([(cxg - half[:, 0]) / img_w,
                       (cyg - half[:, 1]) / img_h,
                       (cxg + half[:, 0]) / img_w,
                       (cyg + half[:, 1]) / img_h], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           boxes.shape)
    return {"Boxes": boxes, "Variances": var}


@register_op("anchor_generator", no_grad_inputs=("Input",))
def anchor_generator(ctx):
    """ref: anchor_generator_op.h — RPN-style anchors in IMAGE coordinates
    (unnormalized, unlike prior_box)."""
    feat = ctx.input("Input")
    sizes = [float(v) for v in ctx.attr("anchor_sizes")]
    ratios = [float(v) for v in ctx.attr("aspect_ratios") or [1.0]]
    variances = [float(v) for v in ctx.attr("variances") or
                 [0.1, 0.1, 0.2, 0.2]]
    stride = [float(v) for v in ctx.attr("stride")]
    offset = ctx.attr("offset", 0.5)
    fh, fw = feat.shape[2], feat.shape[3]

    whs = []
    for r in ratios:
        for s in sizes:
            area = stride[0] * stride[1]
            area_ratios = area / r
            base_w = round(math.sqrt(area_ratios))
            base_h = round(base_w * r)
            scale_w = s / stride[0]
            scale_h = s / stride[1]
            whs.append((scale_w * base_w / 2.0, scale_h * base_h / 2.0))
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * stride[0]
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * stride[1]
    half = jnp.asarray(whs, jnp.float32)
    cxg = jnp.broadcast_to(cx[None, :, None], (fh, fw, half.shape[0]))
    cyg = jnp.broadcast_to(cy[:, None, None], (fh, fw, half.shape[0]))
    anchors = jnp.stack([cxg - half[:, 0], cyg - half[:, 1],
                         cxg + half[:, 0], cyg + half[:, 1]], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           anchors.shape)
    return {"Anchors": anchors, "Variances": var}


# ---------------------------------------------------------------------------
# box_coder / iou_similarity
# ---------------------------------------------------------------------------


def _center_size(boxes, norm_off):
    w = boxes[..., 2] - boxes[..., 0] + norm_off
    h = boxes[..., 3] - boxes[..., 1] + norm_off
    cx = (boxes[..., 2] + boxes[..., 0]) / 2
    cy = (boxes[..., 3] + boxes[..., 1]) / 2
    return cx, cy, w, h


@register_op("box_coder", no_grad_inputs=("PriorBox", "PriorBoxVar",
                                          "TargetBox"))
def box_coder(ctx):
    prior = ctx.input("PriorBox")       # [M, 4]
    pvar = ctx.input("PriorBoxVar")     # [M, 4] or None
    target = ctx.input("TargetBox")
    code_type = ctx.attr("code_type", "encode_center_size")
    norm = ctx.attr("box_normalized", True)
    off = 0.0 if norm else 1.0
    pcx, pcy, pw, ph = _center_size(prior, off)
    if code_type == "encode_center_size":
        # target [N, 4] -> out [N, M, 4]
        tcx, tcy, tw, th = _center_size(target, off)
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        if pvar is not None:
            out = out / pvar[None, :, :]
    else:
        # decode: target [N, M, 4] deltas -> boxes
        t = target
        if pvar is not None:
            t = t * pvar[None, :, :]
        tcx = t[..., 0] * pw + pcx
        tcy = t[..., 1] * ph + pcy
        tw = jnp.exp(t[..., 2]) * pw
        th = jnp.exp(t[..., 3]) * ph
        out = jnp.stack([tcx - tw / 2, tcy - th / 2,
                         tcx + tw / 2 - off, tcy + th / 2 - off], axis=-1)
    return {"OutputBox": out}


def iou_matrix(a, b, normalized=True):
    """[N,4] x [M,4] -> [N,M] IoU (ref: iou_similarity_op.h IOUSimilarity)."""
    off = 0.0 if normalized else 1.0
    area_a = (a[:, 2] - a[:, 0] + off) * (a[:, 3] - a[:, 1] + off)
    area_b = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
    ix0 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy0 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix1 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy1 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.maximum(ix1 - ix0 + off, 0.0)
    ih = jnp.maximum(iy1 - iy0 + off, 0.0)
    inter = iw * ih
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity", no_grad_inputs=("X", "Y"))
def iou_similarity(ctx):
    return {"Out": iou_matrix(ctx.input("X"), ctx.input("Y"),
                              ctx.attr("box_normalized", True))}


# ---------------------------------------------------------------------------
# bipartite_match / target_assign
# ---------------------------------------------------------------------------


def _bipartite_match_one(dist):
    """Greedy global-max matching (ref bipartite_match_op.cc:104 — pick the
    best (row, col) among unmatched rows/cols, repeat; dist<=eps never
    matches).  Returns (col_to_row [-1 unmatched], col_dist)."""
    rows, cols = dist.shape
    eps = 1e-6

    def body(_, carry):
        col_to_row, col_dist, row_used = carry
        masked = jnp.where(row_used[:, None] | (col_to_row[None, :] >= 0),
                           -jnp.inf, dist)
        masked = jnp.where(masked < eps, -jnp.inf, masked)
        flat = jnp.argmax(masked)
        i, j = flat // cols, flat % cols
        ok = masked[i, j] > -jnp.inf
        col_to_row = jnp.where(
            ok, col_to_row.at[j].set(i.astype(col_to_row.dtype)),
            col_to_row)
        col_dist = jnp.where(ok, col_dist.at[j].set(dist[i, j]), col_dist)
        row_used = jnp.where(ok, row_used.at[i].set(True), row_used)
        return col_to_row, col_dist, row_used

    init = (jnp.full((cols,), -1, jnp.int32),
            jnp.zeros((cols,), dist.dtype),
            jnp.zeros((rows,), bool))
    col_to_row, col_dist, _ = jax.lax.fori_loop(0, min(rows, cols), body, init)
    return col_to_row, col_dist


@register_op("bipartite_match", no_grad_inputs=("DistMat",))
def bipartite_match(ctx):
    dist = ctx.input("DistMat")
    lod = ctx.in_lod("DistMat")
    match_type = ctx.attr("match_type", "bipartite")
    overlap_threshold = ctx.attr("dist_threshold", 0.5)
    if lod:
        offsets = lod[-1]
        segments = [(int(offsets[i]), int(offsets[i + 1]))
                    for i in range(len(offsets) - 1)]
    else:
        segments = [(0, dist.shape[0])]
    idx_rows, dist_rows = [], []
    for s, e in segments:
        c2r, cd = _bipartite_match_one(dist[s:e])
        if match_type == "per_prediction":
            # additionally match unmatched cols to their argmax row when
            # overlap exceeds the threshold (ref :151 ArgMaxMatch)
            best_row = jnp.argmax(dist[s:e], axis=0).astype(jnp.int32)
            best = jnp.max(dist[s:e], axis=0)
            extra = (c2r < 0) & (best >= overlap_threshold)
            c2r = jnp.where(extra, best_row, c2r)
            cd = jnp.where(extra, best, cd)
        idx_rows.append(c2r)
        dist_rows.append(cd)
    return {"ColToRowMatchIndices": jnp.stack(idx_rows),
            "ColToRowMatchDist": jnp.stack(dist_rows)}


@register_op("target_assign", no_grad_inputs=("X", "MatchIndices",
                                              "NegIndices"))
def target_assign(ctx):
    x = ctx.input("X")                   # [sum_rows, P, K] (LoD rows)
    match = ctx.input("MatchIndices")    # [N, M] int32, -1 = mismatch
    mismatch_value = ctx.attr("mismatch_value", 0)
    lod = ctx.in_lod("X")
    n, m = match.shape
    k = x.shape[-1]
    p = x.shape[1]
    offsets = lod[-1] if lod else tuple(range(n + 1))
    off = jnp.asarray([int(offsets[i]) for i in range(n)])[:, None]  # [N,1]
    w_off = jnp.arange(m) % p
    safe = jnp.maximum(match, 0)
    rows = off + safe                    # [N, M] row into x
    gathered = x[rows, w_off[None, :], :]          # [N, M, K]
    matched = (match > -1)[..., None]
    out = jnp.where(matched, gathered,
                    jnp.asarray(mismatch_value, x.dtype))
    wt = matched.astype(jnp.float32)
    neg = ctx.input("NegIndices")
    if neg is not None and tuple(neg.shape) == tuple(wt.shape[:2]):
        # mask form (mine_hard_examples emits a same-shape [N, M] 0/1
        # selection): selected negatives get weight 1, targets stay
        # mismatch_value
        wt = jnp.where(neg.astype(bool)[..., None], 1.0, wt)
    elif neg is not None:
        # padded-index form with LoD (ref target_assign_op.h
        # NegTargetAssignFunctor): rows map to images via the LoD
        neg_lod = ctx.in_lod("NegIndices")
        noff = neg_lod[-1] if neg_lod else (0, int(neg.shape[0]))
        nidx = neg.reshape(-1).astype(jnp.int32)
        batch = jnp.concatenate([
            jnp.full((int(noff[i + 1]) - int(noff[i]),), i, jnp.int32)
            for i in range(len(noff) - 1)]) if len(noff) > 1 \
            else jnp.zeros_like(nidx)
        wt = wt.at[batch, nidx].set(1.0)
    return {"Out": out, "OutWeight": wt}


# ---------------------------------------------------------------------------
# multiclass_nms — eager host op (data-dependent output count)
# ---------------------------------------------------------------------------


def _nms_one(boxes, scores, score_threshold, nms_top_k, nms_threshold,
             eta, normalized=True):
    """Single-class hard-NMS on host numpy (ref multiclass_nms_op.cc:66)."""
    keep = []
    idx = np.argsort(-scores)
    idx = idx[scores[idx] > score_threshold]
    if nms_top_k > -1:
        idx = idx[:nms_top_k]
    adaptive = nms_threshold
    sel = list(idx)
    out = []
    while sel:
        i = sel.pop(0)
        out.append(i)
        if not sel:
            break
        a = boxes[i]
        rest = np.array(sel)
        b = boxes[rest]
        off = 0.0 if normalized else 1.0
        ix0 = np.maximum(a[0], b[:, 0]); iy0 = np.maximum(a[1], b[:, 1])
        ix1 = np.minimum(a[2], b[:, 2]); iy1 = np.minimum(a[3], b[:, 3])
        iw = np.maximum(ix1 - ix0 + off, 0); ih = np.maximum(iy1 - iy0 + off, 0)
        inter = iw * ih
        area_a = (a[2] - a[0] + off) * (a[3] - a[1] + off)
        area_b = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
        iou = np.where(area_a + area_b - inter > 0,
                       inter / (area_a + area_b - inter), 0)
        sel = [s for s, v in zip(rest, iou) if v <= adaptive]
        if eta < 1 and adaptive > 0.5:
            adaptive *= eta
    return out


@register_op("multiclass_nms", no_grad_inputs=("BBoxes", "Scores"))
def multiclass_nms(ctx):
    """Host (eager) op.  BBoxes [N, M, 4], Scores [N, C, M] ->
    LoD output [num_kept, 6] = (label, score, x0, y0, x1, y1) per image
    (ref: multiclass_nms_op.cc MultiClassOutput)."""
    bboxes = np.asarray(ctx.input("BBoxes"))
    scores = np.asarray(ctx.input("Scores"))
    bg = ctx.attr("background_label", 0)
    score_threshold = ctx.attr("score_threshold", 0.0)
    nms_top_k = ctx.attr("nms_top_k", -1)
    nms_threshold = ctx.attr("nms_threshold", 0.3)
    eta = ctx.attr("nms_eta", 1.0)
    keep_top_k = ctx.attr("keep_top_k", -1)
    normalized = ctx.attr("normalized", True)

    all_out, lod = [], [0]
    for n in range(bboxes.shape[0]):
        dets = []
        for c in range(scores.shape[1]):
            if c == bg:
                continue
            kept = _nms_one(bboxes[n], scores[n, c], score_threshold,
                            nms_top_k, nms_threshold, eta, normalized)
            for i in kept:
                dets.append((scores[n, c, i], c, i))
        if keep_top_k > -1 and len(dets) > keep_top_k:
            dets.sort(key=lambda t: -t[0])
            dets = dets[:keep_top_k]
        for score, c, i in dets:
            all_out.append([float(c), float(score)] + list(bboxes[n, i]))
        lod.append(len(all_out))
    if not all_out:
        out = np.zeros((1, 1), np.float32)
        out[0, 0] = -1.0
        return {"Out": out, "Out@LOD": [(tuple(lod),)]}
    return {"Out": np.asarray(all_out, np.float32),
            "Out@LOD": [(tuple(lod),)]}


# ---------------------------------------------------------------------------
# roi_pool / polygon_box_transform
# ---------------------------------------------------------------------------


@register_op("roi_pool", no_grad_inputs=("ROIs",))
def roi_pool(ctx):
    """ref: roi_pool_op.* — max-pool each ROI into pooled_h x pooled_w.
    Vectorized as a masked max over the full feature map per output bin."""
    x = ctx.input("X")          # [N, C, H, W]
    rois = ctx.input("ROIs")    # [R, 4] (x0, y0, x1, y1), LoD maps roi->image
    scale = ctx.attr("spatial_scale", 1.0)
    ph = ctx.attr("pooled_height", 1)
    pw = ctx.attr("pooled_width", 1)
    lod = ctx.in_lod("ROIs")
    n, c, h, w = x.shape
    r = rois.shape[0]
    if lod:
        offsets = lod[-1]
        batch_of_roi = np.zeros((r,), np.int32)
        for i in range(len(offsets) - 1):
            batch_of_roi[int(offsets[i]): int(offsets[i + 1])] = i
        batch_of_roi = jnp.asarray(batch_of_roi)
    else:
        batch_of_roi = jnp.zeros((r,), jnp.int32)

    x0 = jnp.round(rois[:, 0] * scale).astype(jnp.int32)
    y0 = jnp.round(rois[:, 1] * scale).astype(jnp.int32)
    x1 = jnp.round(rois[:, 2] * scale).astype(jnp.int32)
    y1 = jnp.round(rois[:, 3] * scale).astype(jnp.int32)
    rh = jnp.maximum(y1 - y0 + 1, 1)
    rw = jnp.maximum(x1 - x0 + 1, 1)

    iy = jnp.arange(h)
    ix = jnp.arange(w)

    def one_roi(b, xx0, yy0, rrh, rrw):
        img = x[b]  # [C, H, W]
        # bin boundaries (ref: floor/ceil of fractional bin edges)
        py = jnp.arange(ph)
        px = jnp.arange(pw)
        hstart = yy0 + jnp.floor(py * rrh / ph).astype(jnp.int32)
        hend = yy0 + jnp.ceil((py + 1) * rrh / ph).astype(jnp.int32)
        wstart = xx0 + jnp.floor(px * rrw / pw).astype(jnp.int32)
        wend = xx0 + jnp.ceil((px + 1) * rrw / pw).astype(jnp.int32)
        hmask = (iy[None, :] >= jnp.clip(hstart, 0, h)[:, None]) & \
                (iy[None, :] < jnp.clip(hend, 0, h)[:, None])   # [ph, H]
        wmask = (ix[None, :] >= jnp.clip(wstart, 0, w)[:, None]) & \
                (ix[None, :] < jnp.clip(wend, 0, w)[:, None])   # [pw, W]
        m = hmask[:, None, :, None] & wmask[None, :, None, :]   # [ph,pw,H,W]
        vals = jnp.where(m[None], img[:, None, None, :, :], -jnp.inf)
        out = jnp.max(vals, axis=(-1, -2))                      # [C, ph, pw]
        return jnp.where(jnp.isfinite(out), out, 0.0)

    out = jax.vmap(one_roi)(batch_of_roi, x0, y0, rh, rw)
    return {"Out": out.astype(x.dtype)}


@register_op("polygon_box_transform", no_grad_inputs=("Input",))
def polygon_box_transform(ctx):
    """ref: polygon_box_transform_op.cc — per-pixel quad offsets to absolute
    coords: odd channels add 4*x of the pixel column, even add 4*y of row
    (channel pairs are (x, y) offsets)."""
    x = ctx.input("Input")  # [N, C(=8), H, W]
    n, c, h, w = x.shape
    col = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    row = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    is_x = (jnp.arange(c) % 2 == 0)[None, :, None, None]
    base = jnp.where(is_x, 4 * col, 4 * row)
    return {"Output": base - x}


# ---------------------------------------------------------------------------
# mine_hard_examples
# ---------------------------------------------------------------------------


@register_op("mine_hard_examples",
             no_grad_inputs=("ClsLoss", "LocLoss", "MatchIndices",
                             "MatchDist"))
def mine_hard_examples(ctx):
    """ref: mine_hard_examples_op.cc (max_negative mining): rank negatives
    by loss, keep neg_pos_ratio * num_pos per sample; outputs the updated
    match indices (hard negatives stay -1, easy negatives set to -2 ... the
    reference emits NegIndices LoD; here we emit a same-shape mask form
    UpdatedMatchIndices + NegIndices as a padded [N, max_neg] index tensor
    with LoD)."""
    cls_loss = ctx.input("ClsLoss")         # [N, M]
    loc_loss = ctx.input("LocLoss")
    match = ctx.input("MatchIndices")       # [N, M]
    match_dist = ctx.input("MatchDist")
    neg_ratio = ctx.attr("neg_pos_ratio", 1.0)
    neg_dist_threshold = ctx.attr("neg_dist_threshold", 0.5)
    mining = ctx.attr("mining_type", "max_negative")
    if mining != "max_negative":
        raise NotImplementedError("only max_negative mining is supported")
    loss = cls_loss if loc_loss is None else cls_loss + \
        (loc_loss if ctx.attr("sample_size", 0) else 0 * loc_loss)
    n, m = match.shape
    is_neg = match < 0
    if match_dist is not None:
        # ref mine_hard_examples_op.h: a prior only qualifies as a
        # negative candidate when its best overlap is BELOW the
        # neg_dist_threshold — semi-overlapping priors are ignored
        is_neg = is_neg & (match_dist < neg_dist_threshold)
    num_pos = jnp.sum(match >= 0, axis=1)
    num_neg = jnp.minimum((num_pos * neg_ratio).astype(jnp.int32),
                          jnp.sum(is_neg, axis=1))
    neg_loss = jnp.where(is_neg, loss, -jnp.inf)
    order = jnp.argsort(-neg_loss, axis=1)            # hardest first
    rank = jnp.argsort(order, axis=1)
    selected = rank < num_neg[:, None]                # [N, M] hard negatives
    updated = jnp.where(is_neg & ~selected, -2, match)  # -2: ignored easy neg
    return {"UpdatedMatchIndices": updated, "NegIndices": selected}
