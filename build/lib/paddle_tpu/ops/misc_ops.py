"""Remaining reference op-parity stragglers (ref: minus_op.cc, cos_sim_op.*,
l1_norm_op.*, norm_op.*, bilinear_tensor_product_op.*, conv_shift_op.*,
modified_huber_loss_op.*, label_smooth_op.*, fill_op.cc, flatten_op.cc
(flatten2/squeeze2/unsqueeze2 emit XShape), random_crop_op.*,
extract_rows_op.cc / split_ids_op.* / merge_ids_op.* /
split_selected_rows_op.* (the SelectedRows utility family),
save_op.cc:36 / load_op.cc:24 / save_combine / load_combine / delete_var
(in-graph checkpoint ops), get_places_op.cc, detection_map_op.*)."""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_grad, register_op


# ---------------------------------------------------------------------------
# dense math stragglers
# ---------------------------------------------------------------------------


@register_op("minus")
def minus(ctx):
    return {"Out": ctx.input("X") - ctx.input("Y")}


@register_op("cos_sim")
def cos_sim(ctx):
    x, y = ctx.input("X"), ctx.input("Y")  # [N, D], [N or 1, D]
    xn = jnp.sqrt(jnp.sum(x * x, -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, -1, keepdims=True))
    out = jnp.sum(x * y, -1, keepdims=True) / jnp.maximum(xn * yn, 1e-12)
    return {"Out": out, "XNorm": xn, "YNorm": yn}


@register_op("l1_norm")
def l1_norm(ctx):
    return {"Out": jnp.sum(jnp.abs(ctx.input("X"))).reshape(1)}


@register_op("norm")
def norm(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", 1)
    eps = ctx.attr("epsilon", 1e-10)
    n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    return {"Out": x / n, "Norm": n}


@register_op("bilinear_tensor_product")
def bilinear_tensor_product(ctx):
    x, y = ctx.input("X"), ctx.input("Y")        # [N, M], [N, P]
    w = ctx.input("Weight")                      # [O, M, P]
    bias = ctx.input("Bias")                     # [1, O] or None
    out = jnp.einsum("nm,omp,np->no", x, w, y)
    if bias is not None:
        out = out + bias
    return {"Out": out}


@register_op("conv_shift")
def conv_shift(ctx):
    """Circular correlation (ref conv_shift_op.cc): Out[i, j] =
    sum_k X[i, (j + k - M//2) mod N] * Y[i, k]."""
    x, y = ctx.input("X"), ctx.input("Y")        # [B, N], [B, M]
    n, m = x.shape[1], y.shape[1]
    half = m // 2
    idx = (jnp.arange(n)[:, None] + jnp.arange(m)[None, :] - half) % n
    return {"Out": jnp.einsum("bnm,bm->bn", x[:, idx], y)}


@register_op("modified_huber_loss", no_grad_inputs=("Y",))
def modified_huber_loss(ctx):
    x, y = ctx.input("X"), ctx.input("Y")  # prob in [0,1], label {0,1}
    t = 2.0 * y.astype(x.dtype) - 1.0      # {-1, +1}
    z = x * t
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, (1.0 - z) ** 2, 0.0))
    return {"Out": loss, "IntermediateVal": z}


@register_op("label_smooth", no_grad_inputs=("PriorDist",))
def label_smooth(ctx):
    x = ctx.input("X")
    prior = ctx.input("PriorDist")
    eps = ctx.attr("epsilon", 0.0)
    if prior is not None:
        return {"Out": (1.0 - eps) * x + eps * prior}
    return {"Out": (1.0 - eps) * x + eps / x.shape[-1]}


@register_op("fill")
def fill(ctx):
    dt = ctx.attr("dtype", 5)
    from ..fluid import core

    vals = np.array(ctx.attr("value"), core.np_dtype(dt))
    return {"Out": vals.reshape(ctx.attr("shape"))}


@register_op("random_crop", stateful=True, no_grad_inputs=("X", "Seed"))
def random_crop(ctx):
    """Per-INSTANCE random crop windows (ref random_crop_op.h draws fresh
    offsets per example, not one window for the whole batch)."""
    x = ctx.input("X")
    shape = list(ctx.attr("shape"))  # crop dims (trailing)
    key = ctx.rng()
    seed = int(ctx.attr("startup_seed", 0) or 0)
    if seed:
        # distinct reproducible stream per user seed (on top of the
        # program-seeded rng, which already varies per step)
        key = jax.random.fold_in(key, seed)
    nd = len(shape)
    lead = x.ndim - nd
    maxs = jnp.asarray([x.shape[lead + i] - shape[i] for i in range(nd)],
                       jnp.int32)

    def crop_nd(xi, k, n_lead):
        """Crop the trailing nd dims of xi (rank n_lead + nd)."""
        offs = jax.random.randint(k, (nd,), 0, maxs + 1, jnp.int32)
        starts = [jnp.int32(0)] * n_lead + [offs[i] for i in range(nd)]
        sizes = list(xi.shape[:n_lead]) + shape
        return jax.lax.dynamic_slice(xi, starts, sizes)

    if lead >= 1:
        # per-INSTANCE windows over dim 0
        keys = jax.random.split(key, x.shape[0])
        out = jax.vmap(lambda xi, k: crop_nd(xi, k, lead - 1))(x, keys)
    else:
        out = crop_nd(x, key, 0)
    return {"Out": out, "SeedOut": jnp.zeros((1,), jnp.int64)}


# ---------------------------------------------------------------------------
# shape variants emitting XShape (ref flatten_op.cc flatten2/squeeze2/
# unsqueeze2 — XShape carries the pre-op shape for the grad op)
# ---------------------------------------------------------------------------


def _xshape(x):
    return jnp.zeros((0,) + tuple(x.shape), x.dtype)


@register_op("flatten2")
def flatten2(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    return {"Out": x.reshape(lead, -1), "XShape": _xshape(x)}


@register_op("squeeze2")
def squeeze2(ctx):
    x = ctx.input("X")
    axes = [a % x.ndim for a in (ctx.attr("axes", []) or [])]
    if axes:
        shape = [s for i, s in enumerate(x.shape)
                 if not (i in axes and s == 1)]
    else:
        shape = [s for s in x.shape if s != 1]
    return {"Out": x.reshape(shape), "XShape": _xshape(x)}


@register_op("unsqueeze2")
def unsqueeze2(ctx):
    x = ctx.input("X")
    shape = list(x.shape)
    for a in sorted(ctx.attr("axes", [])):
        shape.insert(a if a >= 0 else a + len(shape) + 1, 1)
    return {"Out": x.reshape(shape), "XShape": _xshape(x)}


# ---------------------------------------------------------------------------
# SelectedRows utilities (ref extract_rows_op.cc, split_ids_op.*,
# merge_ids_op.*, split_selected_rows_op.* — the pserver sharding helpers;
# here they serve manual sharding / inspection of sparse values)
# ---------------------------------------------------------------------------


@register_op("extract_rows", no_grad_inputs=("X",))
def extract_rows(ctx):
    from ..fluid.selected_rows import SelectedRows

    x = ctx.input("X")
    if not isinstance(x, SelectedRows):
        raise TypeError("extract_rows expects a SelectedRows input")
    return {"Out": x.rows.reshape(-1, 1).astype(jnp.int64)}


@register_op("split_ids", no_grad_inputs=("Ids",))
def split_ids(ctx):
    """Round-robin id sharding (ref split_ids_op.h: shard = id % N)."""
    ids = ctx.input("Ids").reshape(-1)
    n = ctx.n_outputs("Out")
    outs = []
    for shard in range(n):
        mask = (ids % n) == shard
        # static shapes: emit ids with non-members marked -1, packed front
        sel = jnp.where(mask, ids, -1)
        order = jnp.argsort(~mask)  # members first, stable
        outs.append(jnp.take(sel, order).reshape(-1, 1))
    return {"Out": outs}


@register_op("merge_ids", no_grad_inputs=("Ids", "Rows", "X"))
def merge_ids(ctx):
    """Scatter per-shard rows back to the original id order (ref
    merge_ids_op.h)."""
    ids = ctx.input("Ids").reshape(-1)           # original order
    xs = ctx.inputs_list("X")                    # per-shard value tensors
    rows = ctx.inputs_list("Rows")               # per-shard id lists
    d = xs[0].shape[-1]
    all_rows = jnp.concatenate([r.reshape(-1) for r in rows])
    all_vals = jnp.concatenate([x.reshape(-1, d) for x in xs])
    # out[i] = vals[position of ids[i] in all_rows]
    eq = ids[:, None] == all_rows[None, :]
    pos = jnp.argmax(eq, axis=1)
    out = jnp.take(all_vals, pos, axis=0)
    # an id absent from every shard violates the op contract (ref
    # merge_ids_op.h enforces coverage); cannot raise under trace, so
    # poison those rows with NaN instead of silently returning row 0
    found = jnp.any(eq, axis=1)
    out = jnp.where(found[:, None], out, jnp.asarray(jnp.nan, out.dtype))
    return {"Out": out}


@register_op("split_selected_rows", no_grad_inputs=("X",))
def split_selected_rows(ctx):
    from ..fluid.selected_rows import SelectedRows

    x = ctx.input("X")
    if not isinstance(x, SelectedRows):
        raise TypeError("split_selected_rows expects SelectedRows")
    sections = ctx.attr("height_sections")
    n = len(sections)
    bounds = np.cumsum([0] + list(sections))
    outs = []
    for i in range(n):
        inside = (x.rows >= bounds[i]) & (x.rows < bounds[i + 1])
        rows = jnp.where(inside, x.rows - bounds[i], 0)
        vals = jnp.where(inside[:, None], x.values, 0)
        outs.append(SelectedRows(rows, vals, int(sections[i])))
    return {"Out": outs}


# ---------------------------------------------------------------------------
# in-graph checkpoint ops (ref save_op.cc:36, load_op.cc:24,
# save_combine_op.cc, load_combine_op.cc, delete_var_op.cc) — EAGER host
# ops: they run outside jit so the concrete values can hit the filesystem
# ---------------------------------------------------------------------------


@register_op("save", no_grad_inputs=("X",))
def save_op(ctx):
    path = ctx.attr("file_path")
    if not path.endswith(".npy"):
        path = path + ".npy"  # np.save appends it; keep the guard aligned
    overwrite = ctx.attr("overwrite", True)
    if os.path.exists(path) and not overwrite:
        raise IOError(f"save: {path} exists and overwrite=False")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.save(path, np.asarray(ctx.input("X")), allow_pickle=False)
    return {}


@register_op("load")
def load_op(ctx):
    path = ctx.attr("file_path")
    if not path.endswith(".npy") and os.path.exists(path + ".npy"):
        path = path + ".npy"
    return {"Out": np.load(path)}


@register_op("save_combine", no_grad_inputs=("X",))
def save_combine(ctx):
    path = ctx.attr("file_path")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arrs = [np.asarray(v) for v in ctx.inputs_list("X")]
    np.savez(path, *arrs)
    return {}


@register_op("load_combine")
def load_combine(ctx):
    path = ctx.attr("file_path")
    if not path.endswith(".npz") and os.path.exists(path + ".npz"):
        path = path + ".npz"
    z = np.load(path)
    return {"Out": [z[k] for k in z.files]}


@register_op("delete_var")
def delete_var(ctx):
    return {}


@register_op("get_places")
def get_places(ctx):
    from ..fluid import core

    n = ctx.attr("device_count", 0) or core.get_device_count()
    return {"Out": np.arange(n, dtype=np.int64)}


@register_op("depthwise_conv2d_transpose")
def depthwise_conv2d_transpose(ctx):
    # grouped transpose conv with groups defaulting to the CHANNEL count
    # (matching depthwise_conv2d's default), not 1
    from .registry import ExecContext
    from .nn_ops import conv2d_transpose

    x = ctx.input("Input")
    attrs = dict(ctx.attrs)
    if not attrs.get("groups"):
        attrs["groups"] = int(x.shape[1])
    sub = ExecContext(ctx.op_type, ctx.inputs, ctx.outputs_spec, attrs,
                      ctx._rng_box)
    return conv2d_transpose(sub)
