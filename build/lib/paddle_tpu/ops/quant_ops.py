"""Quantization-aware-training ops (ref: fake_quantize_op.cc,
fake_dequantize_op.cc).

Fake quantization simulates int-k inference inside an fp training graph:
``Out = round(X / scale * (2^(bits-1) - 1))``.  Backward is straight-through
(the reference registers these forward-only; QAT wraps them so gradients
bypass the round) — here each op registers an explicit identity-style grad,
the standard straight-through estimator.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_grad, register_op


def _bin_cnt(bits):
    return float(2 ** (bits - 1) - 1)


@register_op("dequantize_weight", no_grad_inputs=("X", "Scale"))
def dequantize_weight(ctx):
    """Weight-only int8 inference (transpiler/int8_transpiler.py): X is an
    int8 weight living in HBM at 1/4 the bytes; Out = X * scale/127 per
    channel, in the float compute dtype.  XLA fuses the cast+multiply into
    the consuming matmul/conv read, so this costs no extra HBM round-trip —
    the TPU analogue of the reference's int8 analysis pass
    (inference/analysis/, fake_dequantize_op.cc math)."""
    x = ctx.input("X")
    scale = ctx.input("Scale")          # [C] float32 per-channel abs-max
    axis = int(ctx.attr("quant_axis", 0))
    shape = [1] * x.ndim
    shape[axis] = -1
    return {"Out": x.astype(jnp.float32) * (scale.reshape(shape) / 127.0)}


@register_op("fake_quantize_abs_max", no_grad_inputs=())
def fake_quantize_abs_max(ctx):
    x = ctx.input("X")
    bits = ctx.attr("bit_length", 8)
    scale = jnp.max(jnp.abs(x))
    safe = jnp.where(scale > 0, scale, 1.0)
    out = jnp.round(x / safe * _bin_cnt(bits))
    return {"Out": out, "OutScale": scale.reshape(1)}


@register_grad("fake_quantize_abs_max")
def fake_quantize_abs_max_grad(ctx):
    # straight-through estimator: d(round(x/s*c))/dx ~= identity in QAT
    return {"X@GRAD": ctx.input("Out@GRAD")}


@register_op("fake_quantize_range_abs_max",
             no_grad_inputs=("InScale", "Iter"))
def fake_quantize_range_abs_max(ctx):
    """Training-time scale tracking over a sliding window (ref
    fake_quantize_op.cc:72 FindRangeAbsMax): the current batch's abs-max is
    written into OutScales[iter % window]; the running OutScale is the max
    of the window (monotone max once the window has filled)."""
    x = ctx.input("X")
    in_scale = ctx.input("InScale").reshape(())
    it = ctx.input("Iter")
    scales = ctx.cur_out("OutScales")
    window = ctx.attr("window_size", 10000)
    bits = ctx.attr("bit_length", 8)
    is_test = ctx.attr("is_test", False)
    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale = in_scale
        new_scales = scales
        new_iter = it
    else:
        idx = (it.reshape(()) % window).astype(jnp.int32)
        if scales is None:
            scales = jnp.zeros((window,), x.dtype)
        new_scales = scales.at[idx].set(cur)
        scale = jnp.maximum(jnp.max(new_scales), cur)
        new_iter = it + 1
    safe = jnp.where(scale > 0, scale, 1.0)
    out = jnp.round(x / safe * _bin_cnt(bits))
    return {"Out": out, "OutScale": scale.reshape(1),
            "OutScales": new_scales, "IterOut": new_iter}


@register_grad("fake_quantize_range_abs_max")
def fake_quantize_range_abs_max_grad(ctx):
    return {"X@GRAD": ctx.input("Out@GRAD")}


@register_op("fake_dequantize_max_abs", no_grad_inputs=("Scale",))
def fake_dequantize_max_abs(ctx):
    x = ctx.input("X")
    scale = ctx.input("Scale").reshape(())
    max_range = ctx.attr("max_range", 1.0)
    return {"Out": x * (scale / max_range)}


@register_grad("fake_dequantize_max_abs")
def fake_dequantize_max_abs_grad(ctx):
    scale = ctx.input("Scale").reshape(())
    max_range = ctx.attr("max_range", 1.0)
    return {"X@GRAD": ctx.input("Out@GRAD") * (scale / max_range)}
