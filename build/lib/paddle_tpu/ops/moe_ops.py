"""Mixture-of-experts op (expert parallelism).

Beyond the reference (SURVEY.md §2.6: MoE/EP "Absent"); the dense dispatch
formulation and EP sharding live in parallel/moe.py.  The op is a pure JAX
function so the generic vjp grad path (ops/registry.py) differentiates it —
gate values, expert weights and inputs all receive gradients; routing
indices are discrete and correctly get none (straight-through is not used,
matching Switch Transformer).
"""

from __future__ import annotations

from .registry import register_op


@register_op("moe_ffn")
def moe_ffn_op(ctx):
    from ..parallel import moe

    x = ctx.input("X")
    out, aux = moe.moe_ffn(
        x,
        ctx.input("GateW"),
        ctx.input("W1"), ctx.input("B1"),
        ctx.input("W2"), ctx.input("B2"),
        top_k=int(ctx.attr("top_k", 2)),
        capacity_factor=float(ctx.attr("capacity_factor", 1.25)),
        activation=ctx.attr("activation", "relu"))
    res = {"Out": out}
    if ctx.n_outputs("AuxLoss"):
        res["AuxLoss"] = aux
    return res
