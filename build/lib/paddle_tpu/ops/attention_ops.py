"""Attention ops — including sequence-parallel ring attention, a
first-class TPU capability the reference lacks (SURVEY.md §5.7: SP/CP
"Absent"; its sequence story is LoD packing on one device).

``ring_attention`` is mesh-aware: traced under a ShardedTrainStep whose
mesh has an "sp" axis, it runs the ppermute ring (parallel/ring_attention
.py) over ICI; traced single-device (plain Executor) it degrades to the
mathematically identical full-softmax attention, so programs are portable
across places — the same portability contract the reference gives ops via
per-place kernels (op_registry.h OpKernelType).
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


@register_op("ring_attention")
def ring_attention_op(ctx):
    q, k, v = ctx.input("Q"), ctx.input("K"), ctx.input("V")  # [B, H, T, D]
    bias = ctx.input("Bias") if ctx.has_input("Bias") else None
    causal = ctx.attr("causal", False)
    sp_axis = ctx.attr("sp_axis", "sp")
    scale = ctx.attr("scale", 0.0) or None
    from ..parallel import ring_attention as ra
    from ..parallel import spmd

    mesh = spmd.active_mesh()
    if mesh is not None and sp_axis in mesh.axis_names \
            and mesh.shape[sp_axis] > 1:
        out = ra.ring_attention(q, k, v, mesh, sp_axis, causal, scale,
                                bias=bias)
    elif bias is None and _use_flash():
        from .pallas_flash import flash_attention

        out = flash_attention(q, k, v, scale, causal)
    else:
        out = ra.full_attention(q, k, v, causal, scale, bias=bias)
    return {"Out": out}


def _use_flash() -> bool:
    """Opt-in Pallas flash-attention kernel (PADDLE_TPU_FLASH=1).

    Off by default because tunneled TPU transports (axon remote-compile)
    cannot compile Mosaic kernels; on a real TPU VM the kernel compiles
    natively and streams K/V through VMEM (ops/pallas_flash.py)."""
    import os

    return os.environ.get("PADDLE_TPU_FLASH", "").strip().lower() \
        in ("1", "true")
