"""Flash attention as a Pallas TPU kernel.

The hot op of the transformer/BERT path gets a hand-scheduled kernel
(SURVEY.md §7.3: "Pallas only where XLA underperforms"): one grid step
owns a [BLOCK_Q, D] query tile resident in VMEM and streams the K/V tiles
through the MXU with the online-softmax recurrence, so the [T, T] score
matrix never hits HBM.  Accumulation is fp32 in VMEM scratch regardless of
the input dtype (the same master-accumulator discipline as fluid.amp).

Backward: custom_vjp with the standard recompute formulation — dS = P ∘
(dP - rowsum(dO ∘ O)) — expressed in jnp (XLA fuses it well; a Pallas
backward is a further optimization, not a correctness need).

Falls back to interpret mode off-TPU, so the same code path is testable on
the CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale, causal, n_k):
    """Grid step (head, q-block, k-block): one [bq, d] query tile against
    one [bk, d] K/V tile, with the online-softmax state (m, l, acc) carried
    in fp32 VMEM scratch across the (sequential, minormost) k dimension of
    the grid — so VMEM holds only one K/V TILE at a time and t_kv can be
    arbitrarily long."""
    ki = pl.program_id(2)
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]
    # all index math in i32: under the package-wide x64 mode python ints
    # promote to i64, which Mosaic's index ops reject
    q_off = qi * jnp.int32(bq)
    k_off = ki * jnp.int32(bk)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # under causal masking, blocks strictly above the diagonal contribute
    # nothing — skip both MXU contractions for them (~2x FLOPs at long T)
    live = (k_off <= q_off + jnp.int32(bq - 1)) if causal else True

    @pl.when(live)
    def _attend():
        q = q_ref[0].astype(jnp.float32) * jnp.float32(scale)  # [bq, d]
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # [bq, bk]
        if causal:
            qpos = q_off + jax.lax.broadcasted_iota(jnp.int32,
                                                    logits.shape, 0)
            kpos = k_off + jax.lax.broadcasted_iota(jnp.int32,
                                                    logits.shape, 1)
            logits = jnp.where(qpos >= kpos, logits, jnp.float32(NEG_INF))
        m = m_ref[:]
        l = l_ref[:]
        m_new = jnp.maximum(m, jnp.max(logits, axis=1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m - m_new)
        m_ref[:] = m_new
        l_ref[:] = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _flush():
        o_ref[0] = (acc_ref[:] /
                    jnp.maximum(l_ref[:], jnp.float32(1e-30))
                    ).astype(o_ref.dtype)


def _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret):
    from jax.experimental.pallas import tpu as pltpu

    b, h, t, d = q.shape
    t_kv = k.shape[2]
    bq = min(block_q, t)
    bk = min(block_k, t_kv)
    while t % bq:
        bq //= 2
    while t_kv % bk:
        bk //= 2
    n_k = t_kv // bk
    # grid iterates k-blocks innermost: TPU grids run sequentially on a
    # core, so the scratch online-softmax state carries across ki steps
    grid = (b * h, t // bq, n_k)
    qr = q.reshape(b * h, t, d)
    kr = k.reshape(b * h, t_kv, d)
    vr = v.reshape(b * h, t_kv, d)
    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          n_k=n_k),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, s: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, s: (i, s, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, s: (i, s, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j, s: (i, j, 0)),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),   # running max
            pltpu.VMEM((bq, 1), jnp.float32),   # running denominator
            pltpu.VMEM((bq, d), jnp.float32),   # fp32 accumulator
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, t, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, scale=None, causal=False,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=None):
    """softmax(scale * q k^T [+ causal mask]) v, streamed (never

    materializes the [T, T] scores).  q/k/v: [B, H, T, D]."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, scale, causal, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out = flash_attention(q, k, v, scale, causal, block_q, block_k,
                          interpret)
    return out, (q, k, v, out)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, do):
    """Recompute backward (Dao FA2 eq. form): with P the softmax probs,
    dV = Pᵀ dO;  dS = P ∘ (dO Vᵀ - rowsum(dO ∘ O));  dQ = scale · dS K;
    dK = scale · dSᵀ Q."""
    q, k, v, o = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    of = o.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    delta = jnp.sum(dof * of, axis=-1, keepdims=True)
    ds = p * (dp - delta)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
