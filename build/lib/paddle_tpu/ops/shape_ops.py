"""Shape / indexing / layout ops (ref: reshape_op.cc, transpose_op.*,
concat_op.*, split_op.*, gather_op.*, squeeze/unsqueeze, flatten, stack,
slice, expand, pad, one_hot, multiplex, reverse)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op


def _infer_reshape(shape_attr, x):
    """Fluid reshape: 0 keeps the input dim, one -1 is inferred."""
    shape = list(shape_attr)
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        total = int(np.prod(x.shape)) if x.ndim else 1
        shape[shape.index(-1)] = total // known
    return shape


@register_op("reshape")
def reshape(ctx):
    x = ctx.input("X")
    out = x.reshape(_infer_reshape(ctx.attr("shape"), x))
    return {"Out": out}


@register_op("reshape2")
def reshape2(ctx):
    x = ctx.input("X")
    out = x.reshape(_infer_reshape(ctx.attr("shape"), x))
    return {"Out": out, "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("transpose")
def transpose(ctx):
    return {"Out": jnp.transpose(ctx.input("X"), ctx.attr("axis"))}


@register_op("transpose2")
def transpose2(ctx):
    x = ctx.input("X")
    return {"Out": jnp.transpose(x, ctx.attr("axis")),
            "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("concat")
def concat(ctx):
    xs = ctx.inputs_list("X")
    return {"Out": jnp.concatenate(xs, axis=ctx.attr("axis", 0))}


@register_op("split")
def split(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", 0)
    sections = ctx.attr("sections", None)
    num = ctx.attr("num", 0)
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        n = num or ctx.n_outputs("Out")
        outs = jnp.split(x, n, axis=axis)
    return {"Out": list(outs)}


@register_op("squeeze")
def squeeze(ctx):
    x = ctx.input("X")
    axes = ctx.attr("axes", None)
    if axes:
        axes = tuple(a % x.ndim for a in axes)
        axes = tuple(a for a in axes if x.shape[a] == 1)
        out = jnp.squeeze(x, axis=axes) if axes else x
    else:
        out = jnp.squeeze(x)
    return {"Out": out}


@register_op("unsqueeze")
def unsqueeze(ctx):
    x = ctx.input("X")
    for a in sorted(ctx.attr("axes")):
        x = jnp.expand_dims(x, a)
    return {"Out": x}


@register_op("flatten")
def flatten(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    return {"Out": x.reshape(lead, -1)}


@register_op("stack")
def stack(ctx):
    return {"Y": jnp.stack(ctx.inputs_list("X"), axis=ctx.attr("axis", 0))}


@register_op("unstack")
def unstack(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", 0)
    n = x.shape[axis]
    return {"Y": [jnp.squeeze(a, axis) for a in jnp.split(x, n, axis=axis)]}


@register_op("slice")
def slice_op(ctx):
    x = ctx.input("Input")
    axes = ctx.attr("axes")
    starts = ctx.attr("starts")
    ends = ctx.attr("ends")
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    return {"Out": x[tuple(idx)]}


@register_op("expand")
def expand(ctx):
    x = ctx.input("X")
    times = ctx.attr("expand_times")
    return {"Out": jnp.tile(x, times)}


@register_op("expand_as")
def expand_as(ctx):
    x, y = ctx.input("X"), ctx.input("target_tensor") or ctx.input("Y")
    times = [t // s for t, s in zip(y.shape, x.shape)]
    return {"Out": jnp.tile(x, times)}


@register_op("gather", no_grad_inputs=("Index",))
def gather(ctx):
    x = ctx.input("X")
    idx = ctx.input("Index").astype(jnp.int32)
    return {"Out": jnp.take(x, idx.reshape(-1), axis=0)}


@register_op("scatter", no_grad_inputs=("Ids",))
def scatter(ctx):
    x = ctx.input("X")
    ids = ctx.input("Ids").astype(jnp.int32).reshape(-1)
    upd = ctx.input("Updates")
    if ctx.attr("overwrite", True):
        return {"Out": x.at[ids].set(upd)}
    return {"Out": x.at[ids].add(upd)}


@register_op("pad")
def pad(ctx):
    x = ctx.input("X")
    p = ctx.attr("paddings")
    val = ctx.attr("pad_value", 0.0)
    cfg = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, cfg, constant_values=val)}


@register_op("pad2d")
def pad2d(ctx):
    x = ctx.input("X")
    p = ctx.attr("paddings")  # [top, bottom, left, right]
    mode = ctx.attr("mode", "constant")
    val = ctx.attr("pad_value", 0.0)
    fmt = ctx.attr("data_format", "NCHW")
    if fmt == "NCHW":
        cfg = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    else:
        cfg = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    if mode == "constant":
        return {"Out": jnp.pad(x, cfg, constant_values=val)}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": jnp.pad(x, cfg, mode=jmode)}


@register_op("pad_constant_like")
def pad_constant_like(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    val = ctx.attr("pad_value", 0.0)
    cfg = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": jnp.pad(y, cfg, constant_values=val)}


@register_op("crop")
def crop(ctx):
    x = ctx.input("X")
    offsets = ctx.attr("offsets")
    shape = ctx.attr("shape")
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return {"Out": x[idx]}


@register_op("reverse")
def reverse(ctx):
    x = ctx.input("X")
    return {"Out": jnp.flip(x, axis=tuple(ctx.attr("axis")))}


@register_op("one_hot", no_grad_inputs=("X",))
def one_hot(ctx):
    x = ctx.input("X").astype(jnp.int32)
    depth = ctx.attr("depth")
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x.reshape(x.shape[:-1])
    return {"Out": jax.nn.one_hot(x, depth, dtype=jnp.float32)}


@register_op("shape", no_grad_inputs=("Input",))
def shape_op(ctx):
    return {"Out": jnp.array(ctx.input("Input").shape, jnp.int32)}


@register_op("multiplex", no_grad_inputs=("Ids",))
def multiplex(ctx):
    ids = ctx.input("Ids").astype(jnp.int32).reshape(-1)
    xs = jnp.stack(ctx.inputs_list("X"), axis=0)  # [n_candidates, N, D]
    rows = jnp.arange(xs.shape[1])
    return {"Out": xs[ids, rows]}


@register_op("where", no_grad_inputs=("Condition",))
def where(ctx):
    return {"Out": jnp.where(ctx.input("Condition"), ctx.input("X"), ctx.input("Y"))}


@register_op("tile")
def tile(ctx):
    return {"Out": jnp.tile(ctx.input("X"), ctx.attr("repeat_times"))}


@register_op("bilinear_interp")
def bilinear_interp(ctx):
    x = ctx.input("X")  # NCHW
    out_h = ctx.attr("out_h")
    out_w = ctx.attr("out_w")
    n, c, h, w = x.shape
    out = jax.image.resize(x, (n, c, out_h, out_w), method="bilinear")
    return {"Out": out}


@register_op("nearest_interp")
def nearest_interp(ctx):
    x = ctx.input("X")
    out_h = ctx.attr("out_h")
    out_w = ctx.attr("out_w")
    n, c, h, w = x.shape
    return {"Out": jax.image.resize(x, (n, c, out_h, out_w), method="nearest")}
