"""Metric ops (ref: accuracy_op.*, auc_op.*, mean_iou_op, precision_recall)."""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


@register_op("accuracy", no_grad_inputs=("Out", "Indices", "Label"))
def accuracy(ctx):
    indices = ctx.input("Indices")  # [N, k] top-k indices
    label = ctx.input("Label")      # [N, 1]
    if label.ndim == 2:
        label = label.reshape(-1)
    hit = jnp.any(indices == label[:, None].astype(indices.dtype), axis=1)
    correct = jnp.sum(hit.astype(jnp.int32))
    total = jnp.array(indices.shape[0], jnp.int32)
    acc = correct.astype(jnp.float32) / total.astype(jnp.float32)
    return {"Accuracy": acc.reshape(1), "Correct": correct.reshape(1),
            "Total": total.reshape(1)}


@register_op("auc", no_grad_inputs=("Predict", "Label", "StatPos", "StatNeg"))
def auc(ctx):
    """Streaming AUC over histogram buckets (ref: auc_op.h)."""
    predict = ctx.input("Predict")  # [N, 2] probs
    label = ctx.input("Label").reshape(-1)
    stat_pos = ctx.input("StatPos")
    stat_neg = ctx.input("StatNeg")
    num_thresholds = ctx.attr("num_thresholds", 4095)
    pos_prob = predict[:, -1]
    bucket = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32), 0,
                      num_thresholds)
    is_pos = (label > 0)
    stat_pos = stat_pos.at[bucket].add(is_pos.astype(stat_pos.dtype))
    stat_neg = stat_neg.at[bucket].add((~is_pos).astype(stat_neg.dtype))
    # integrate: iterate buckets from high threshold to low
    pos_cum = jnp.cumsum(stat_pos[::-1])
    neg_cum = jnp.cumsum(stat_neg[::-1])
    tot_pos = pos_cum[-1]
    tot_neg = neg_cum[-1]
    # trapezoid area between consecutive operating points
    prev_pos = jnp.concatenate([jnp.zeros(1, pos_cum.dtype), pos_cum[:-1]])
    prev_neg = jnp.concatenate([jnp.zeros(1, neg_cum.dtype), neg_cum[:-1]])
    area = jnp.sum((neg_cum - prev_neg) * (pos_cum + prev_pos) / 2.0)
    auc_val = jnp.where((tot_pos > 0) & (tot_neg > 0),
                        area / jnp.maximum(tot_pos * tot_neg, 1e-12), 0.0)
    return {"AUC": auc_val.reshape(1).astype(jnp.float64)
            if auc_val.dtype == jnp.float64 else auc_val.reshape(1),
            "StatPosOut": stat_pos, "StatNegOut": stat_neg}


@register_op("mean_iou", no_grad_inputs=("Predictions", "Labels"))
def mean_iou(ctx):
    pred = ctx.input("Predictions").reshape(-1).astype(jnp.int32)
    label = ctx.input("Labels").reshape(-1).astype(jnp.int32)
    n = ctx.attr("num_classes")
    conf = jnp.zeros((n, n), jnp.float32).at[label, pred].add(1.0)
    inter = jnp.diag(conf)
    union = conf.sum(0) + conf.sum(1) - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1e-12), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    return {"OutMeanIou": miou.reshape(1), "OutWrong": (conf.sum(1) - inter),
            "OutCorrect": inter}


@register_op("positive_negative_pair",
             no_grad_inputs=("Score", "Label", "QueryID", "Weight",
                             "AccumulatePositivePair",
                             "AccumulateNegativePair",
                             "AccumulateNeutralPair"))
def positive_negative_pair(ctx):
    """Ranking-pair metric (ref: positive_negative_pair_op.h): within each
    query, every differently-labeled doc pair is positive when score order
    agrees with label order.  Reference-exact semantics incl. its
    equal-score behavior (counts as neutral AND negative) and per-pair
    weight (w_i + w_j)/2.  O(n^2) masks instead of the reference's per-
    query hash map — static shapes for XLA."""
    score = ctx.input("Score")
    label = ctx.input("Label").reshape(-1).astype(jnp.float32)
    query = ctx.input("QueryID").reshape(-1)
    col = int(ctx.attr("column", 0))  # ref default 0
    s = score[:, col].astype(jnp.float32)
    w_in = ctx.input("Weight")
    w = (w_in.reshape(-1).astype(jnp.float32) if w_in is not None
         else jnp.ones_like(s))

    same_q = query[:, None] == query[None, :]
    n = s.shape[0]
    upper = jnp.triu(jnp.ones((n, n), bool), k=1)
    diff_label = label[:, None] != label[None, :]
    pair = same_q & upper & diff_label
    pw = (w[:, None] + w[None, :]) * 0.5
    ds = s[:, None] - s[None, :]
    dl = label[:, None] - label[None, :]
    neu = jnp.sum(jnp.where(pair & (ds == 0), pw, 0.0))
    pos = jnp.sum(jnp.where(pair & (ds * dl > 0), pw, 0.0))
    neg = jnp.sum(jnp.where(pair & ~(ds * dl > 0), pw, 0.0))

    acc_p = ctx.input("AccumulatePositivePair")
    acc_n = ctx.input("AccumulateNegativePair")
    acc_u = ctx.input("AccumulateNeutralPair")
    if acc_p is not None:
        pos = pos + acc_p.reshape(-1)[0]
    if acc_n is not None:
        neg = neg + acc_n.reshape(-1)[0]
    if acc_u is not None:
        neu = neu + acc_u.reshape(-1)[0]
    return {"PositivePair": pos.reshape(1), "NegativePair": neg.reshape(1),
            "NeutralPair": neu.reshape(1)}


@register_op("precision_recall",
             no_grad_inputs=("MaxProbs", "Indices", "Labels", "Weights",
                             "StatesInfo"))
def precision_recall(ctx):
    """Multi-class precision/recall/F1 (ref: precision_recall_op.h).
    States per class: [TP, FP, TN, FN]; metrics: [macro-P, macro-R,
    macro-F1, micro-P, micro-R, micro-F1], with the reference's
    empty-class convention (precision/recall default 1.0, F1 0.0)."""
    idx = ctx.input("Indices").reshape(-1).astype(jnp.int32)
    label = ctx.input("Labels").reshape(-1).astype(jnp.int32)
    cls = int(ctx.attr("class_number"))
    w_in = ctx.input("Weights")
    w = (w_in.reshape(-1).astype(jnp.float32) if w_in is not None
         else jnp.ones(idx.shape, jnp.float32))

    hit = idx == label
    oh_idx = jnp.zeros((idx.shape[0], cls),
                       jnp.float32).at[jnp.arange(idx.shape[0]), idx].set(1.0)
    oh_lab = jnp.zeros((idx.shape[0], cls),
                       jnp.float32).at[jnp.arange(idx.shape[0]),
                                       label].set(1.0)
    wv = w[:, None]
    tp = jnp.sum(jnp.where(hit[:, None], oh_idx * wv, 0.0), axis=0)
    fp = jnp.sum(jnp.where(~hit[:, None], oh_idx * wv, 0.0), axis=0)
    fn = jnp.sum(jnp.where(~hit[:, None], oh_lab * wv, 0.0), axis=0)
    # TN per class j: every sample adds w except those whose idx or label
    # is j (hit samples subtract once: idx == label == j)
    total = jnp.sum(w)
    tn = total - tp - fp - fn
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)

    def metrics(states):
        tp_, fp_, tn_, fn_ = (states[:, 0], states[:, 1], states[:, 2],
                              states[:, 3])
        prec = jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, 1e-12),
                         1.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, 1e-12),
                        1.0)
        # macro-F1 is F1(macro-P, macro-R), NOT the mean of per-class
        # F1s (ref precision_recall_op.h ComputeMetrics)
        map_, mar = prec.mean(), rec.mean()
        maf = jnp.where(map_ + mar > 0,
                        2 * map_ * mar / jnp.maximum(map_ + mar, 1e-12),
                        0.0)
        macro = jnp.stack([map_, mar, maf])
        stp, sfp, sfn = tp_.sum(), fp_.sum(), fn_.sum()
        mp = jnp.where(stp + sfp > 0, stp / jnp.maximum(stp + sfp, 1e-12),
                       1.0)
        mr = jnp.where(stp + sfn > 0, stp / jnp.maximum(stp + sfn, 1e-12),
                       1.0)
        mf = jnp.where(mp + mr > 0, 2 * mp * mr / jnp.maximum(mp + mr,
                                                              1e-12), 0.0)
        return jnp.concatenate([macro, jnp.stack([mp, mr, mf])])

    batch_metrics = metrics(batch_states)
    prev = ctx.input("StatesInfo")
    accum_states = batch_states + (prev.astype(jnp.float32)
                                   if prev is not None else 0.0)
    accum_metrics = metrics(accum_states)
    return {"BatchMetrics": batch_metrics.astype(jnp.float64),
            "AccumMetrics": accum_metrics.astype(jnp.float64),
            "AccumStatesInfo": accum_states}
