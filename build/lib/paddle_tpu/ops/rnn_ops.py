"""Recurrent ops: dynamic_lstm / dynamic_lstmp / dynamic_gru + unit cells.

ref: paddle/fluid/operators/{lstm,lstmp,gru,gru_unit,lstm_unit}_op.cc.

TPU design: the reference reorders packed sequences into length-sorted
batches (operators/math/sequence2batch.h) and runs a per-timestep CPU/CUDA
cell kernel.  Here the packed input is padded to [num_seq, T, ...] with
*static* trace-time lod (executor.trace_block) and the recurrence is one
``lax.scan`` over time with a validity mask — XLA turns the scan body's
matmuls into MXU ops and the whole loop compiles to a single fused kernel.

Gate layouts follow the reference exactly:
 - lstm  Weight = {W_ch, W_ih, W_fh, W_oh}; Bias = {b_c,b_i,b_f,b_o} and,
   with use_peepholes, {W_ic, W_fc, W_oc} appended (lstm_op.cc:125,135).
 - gru   Weight = [W_u | W_r (D x 2D), W_c (D x D)];
   h_t = (1-u_t)*h_{t-1} + u_t*h~_t  (gru_op.cc:144-147).
 - lstm_unit X = [i, f, o, j]; C = C_prev*sig(f+forget_bias)+sig(i)*tanh(j)
   (lstm_unit_op.cc:70).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


_ACTS = {
    "identity": lambda x: x,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
}
_ACT_ENUM = {0: "identity", 1: "sigmoid", 2: "tanh", 3: "relu"}


def _act(name_or_enum, default):
    if name_or_enum is None:
        name_or_enum = default
    if isinstance(name_or_enum, int):
        name_or_enum = _ACT_ENUM[name_or_enum]
    return _ACTS[str(name_or_enum)]


def _pad_indices(off, reverse=False):
    """idx[i, t] = packed row of timestep t of sequence i (sentinel = total
    for padding); plus the inverse map packed row -> (i*T + t)."""
    off = np.asarray(off)
    lens = off[1:] - off[:-1]
    n = len(lens)
    total = int(off[-1])
    T = int(lens.max()) if n else 0
    idx = np.full((n, T), total, np.int64)
    inv = np.zeros((total,), np.int64)
    for i in range(n):
        rows = np.arange(off[i], off[i + 1])
        ts = np.arange(lens[i])
        if reverse:
            ts = lens[i] - 1 - ts
        idx[i, ts] = rows
        inv[rows] = i * T + ts
    mask = (np.arange(T)[None, :] < lens[:, None])
    return idx, inv, mask, n, T


def _to_padded(x, idx):
    xp = jnp.concatenate([x, jnp.zeros((1,) + x.shape[1:], x.dtype)], axis=0)
    return xp[jnp.asarray(idx)]


def _to_packed(padded, inv):
    n, T = padded.shape[0], padded.shape[1]
    flat = padded.reshape((n * T,) + padded.shape[2:])
    return flat[jnp.asarray(inv)]


@register_op("dynamic_lstm", no_grad_inputs=())
def dynamic_lstm(ctx):
    return _lstm_impl(ctx, project=False)


@register_op("dynamic_lstmp")
def dynamic_lstmp(ctx):
    return _lstm_impl(ctx, project=True)


def _lstm_impl(ctx, project):
    x = ctx.input("Input")          # [total, 4D] (pre-projected by mul/fc)
    w = ctx.input("Weight")         # [D, 4D] (lstmp: [P, 4D])
    bias = ctx.input("Bias")        # [1, 4D] (+3D peephole tail)
    h0 = ctx.input("H0")
    c0 = ctx.input("C0")
    off = ctx.seq_offsets("Input")
    use_peep = bool(ctx.attr("use_peepholes", True))
    reverse = bool(ctx.attr("is_reverse", False))
    gate_act = _act(ctx.attr("gate_activation"), "sigmoid")
    cell_act = _act(ctx.attr("cell_activation"), "tanh")
    cand_act = _act(ctx.attr("candidate_activation"), "tanh")
    d = x.shape[1] // 4
    if project:
        proj_w = ctx.input("ProjWeight")   # [D, P]
        proj_act = _act(ctx.attr("proj_activation"), "identity")
        p = proj_w.shape[1]
    idx, inv, mask, n, t_max = _pad_indices(off, reverse)
    xs = jnp.transpose(_to_padded(x, idx), (1, 0, 2))       # [T, n, 4D]
    ms = jnp.asarray(mask.T[:, :, None])                    # [T, n, 1]

    b_gate = bias[:, : 4 * d] if bias is not None else 0.0
    if use_peep and bias is not None and bias.shape[-1] >= 7 * d:
        w_ic = bias[0, 4 * d: 5 * d]
        w_fc = bias[0, 5 * d: 6 * d]
        w_oc = bias[0, 6 * d: 7 * d]
    else:
        w_ic = w_fc = w_oc = None

    h_init = h0 if h0 is not None else jnp.zeros(
        (n, p if project else d), x.dtype)
    c_init = c0 if c0 is not None else jnp.zeros((n, d), x.dtype)

    def step(carry, inp):
        h_prev, c_prev = carry
        x_t, m_t = inp
        gates = x_t + h_prev @ w + b_gate
        g_c, g_i, g_f, g_o = jnp.split(gates, 4, axis=1)
        if w_ic is not None:
            g_i = g_i + w_ic * c_prev
            g_f = g_f + w_fc * c_prev
        i = gate_act(g_i)
        f = gate_act(g_f)
        cand = cand_act(g_c)
        c = f * c_prev + i * cand
        if w_oc is not None:
            g_o = g_o + w_oc * c
        o = gate_act(g_o)
        h = o * cell_act(c)
        if project:
            h = proj_act(h @ proj_w)
        h = jnp.where(m_t, h, h_prev)
        c = jnp.where(m_t, c, c_prev)
        return (h, c), (h, c)

    (_, _), (hs, cs) = lax.scan(step, (h_init, c_init), (xs, ms))
    hidden = _to_packed(jnp.transpose(hs, (1, 0, 2)), inv)
    cell = _to_packed(jnp.transpose(cs, (1, 0, 2)), inv)
    out_slot = "Projection" if project else "Hidden"
    res = {out_slot: hidden, "Cell": cell}
    if ctx.n_outputs("BatchGate"):
        res["BatchGate"] = jnp.zeros_like(x)
    if ctx.n_outputs("BatchCellPreAct"):
        res["BatchCellPreAct"] = jnp.zeros_like(cell)
    if ctx.n_outputs("BatchHidden"):
        res["BatchHidden"] = jnp.zeros_like(hidden)
    return res


@register_op("dynamic_gru")
def dynamic_gru(ctx):
    x = ctx.input("Input")          # [total, 3D] = [xu | xr | xc]
    w = ctx.input("Weight")         # [D, 3D] = [W_u|W_r (D,2D), W_c (D,D)]
    bias = ctx.input("Bias")        # [1, 3D]
    h0 = ctx.input("H0")
    off = ctx.seq_offsets("Input")
    reverse = bool(ctx.attr("is_reverse", False))
    gate_act = _act(ctx.attr("gate_activation"), "sigmoid")
    cand_act = _act(ctx.attr("activation"), "tanh")
    d = x.shape[1] // 3
    w_ur = w[:, : 2 * d]
    w_c = w[:, 2 * d:]
    idx, inv, mask, n, t_max = _pad_indices(off, reverse)
    xs = jnp.transpose(_to_padded(x, idx), (1, 0, 2))
    ms = jnp.asarray(mask.T[:, :, None])
    if bias is not None:
        b_ur, b_c = bias[:, : 2 * d], bias[:, 2 * d:]
    else:
        b_ur = b_c = 0.0
    h_init = h0 if h0 is not None else jnp.zeros((n, d), x.dtype)

    origin_mode = bool(ctx.attr("origin_mode", False))

    def step(h_prev, inp):
        x_t, m_t = inp
        xu, xr, xc = jnp.split(x_t, [d, 2 * d], axis=1)
        ur = gate_act(jnp.concatenate([xu, xr], 1) + h_prev @ w_ur + b_ur)
        u, r = jnp.split(ur, 2, axis=1)
        cand = cand_act(xc + (r * h_prev) @ w_c + b_c)
        if origin_mode:
            h = u * h_prev + (1.0 - u) * cand
        else:
            h = (1.0 - u) * h_prev + u * cand
        h = jnp.where(m_t, h, h_prev)
        return h, h

    _, hs = lax.scan(step, h_init, (xs, ms))
    hidden = _to_packed(jnp.transpose(hs, (1, 0, 2)), inv)
    res = {"Hidden": hidden}
    for slot in ("BatchGate", "BatchResetHiddenPrev", "BatchHidden"):
        if ctx.n_outputs(slot):
            shape = (x.shape[0], 3 * d) if slot == "BatchGate" \
                else (x.shape[0], d)
            res[slot] = jnp.zeros(shape, x.dtype)
    return res


@register_op("gru_unit")
def gru_unit(ctx):
    """ref: gru_unit_op.cc:118-121 (activation attrs are int enums,
    gru_unit_op.h:34)."""
    x = ctx.input("Input")          # [B, 3D]
    h_prev = ctx.input("HiddenPrev")
    w = ctx.input("Weight")
    bias = ctx.input("Bias")
    gate_act = _act(ctx.attr("gate_activation", 1), "sigmoid")
    cand_act = _act(ctx.attr("activation", 2), "tanh")
    d = h_prev.shape[1]
    xb = x + bias if bias is not None else x
    xu, xr, xc = jnp.split(xb, [d, 2 * d], axis=1)
    ur = gate_act(jnp.concatenate([xu, xr], 1) + h_prev @ w[:, : 2 * d])
    u, r = jnp.split(ur, 2, axis=1)
    reset_h = r * h_prev
    cand = cand_act(xc + reset_h @ w[:, 2 * d:])
    h = (1.0 - u) * h_prev + u * cand
    gate = jnp.concatenate([u, r, cand], axis=1)
    return {"Gate": gate, "ResetHiddenPrev": reset_h, "Hidden": h}


@register_op("lstm_unit")
def lstm_unit(ctx):
    """ref: lstm_unit_op.cc:70 — X=[i,f,o,j], forget_bias added to f."""
    x = ctx.input("X")
    c_prev = ctx.input("C_prev")
    fb = float(ctx.attr("forget_bias", 0.0))
    i, f, o, j = jnp.split(x, 4, axis=1)
    c = c_prev * jax.nn.sigmoid(f + fb) + jax.nn.sigmoid(i) * jnp.tanh(j)
    h = c * jax.nn.sigmoid(o)
    return {"C": c, "H": h}
