"""Reduction / sort / topk ops (ref: paddle/fluid/operators/reduce_*,
top_k_op.*, arg_min_max_op, cum_op, argsort)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _reduce_axes(ctx, x):
    dim = ctx.attr("dim", None)
    if ctx.attr("reduce_all", False) or dim is None:
        return None
    if isinstance(dim, int):
        dim = [dim]
    return tuple(d % x.ndim for d in dim)


def _reduce(name, fn):
    @register_op(name)
    def _impl(ctx, _fn=fn):
        x = ctx.input("X")
        axes = _reduce_axes(ctx, x)
        keep = ctx.attr("keep_dim", False)
        return {"Out": _fn(x, axis=axes, keepdims=keep)}
    return _impl


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)


@register_op("cumsum")
def cumsum(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    exclusive = ctx.attr("exclusive", False)
    reverse = ctx.attr("reverse", False)
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    return {"Out": out}


@register_op("arg_max", no_grad_inputs=("X",))
def arg_max(ctx):
    return {"Out": jnp.argmax(ctx.input("X"), axis=ctx.attr("axis", -1)).astype(jnp.int64)}


@register_op("arg_min", no_grad_inputs=("X",))
def arg_min(ctx):
    return {"Out": jnp.argmin(ctx.input("X"), axis=ctx.attr("axis", -1)).astype(jnp.int64)}


@register_op("argsort", no_grad_inputs=("X",))
def argsort(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    idx = jnp.argsort(x, axis=axis)
    return {"Out": jnp.sort(x, axis=axis), "Indices": idx.astype(jnp.int64)}


@register_op("top_k", no_grad_inputs=("X",))
def top_k(ctx):
    x = ctx.input("X")
    k = ctx.attr("k", 1)
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": vals, "Indices": idx.astype(jnp.int64)}
