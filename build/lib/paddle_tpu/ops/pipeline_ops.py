"""Pipeline-parallel layer-stack op.

Mesh-aware like ring_attention (ops/attention_ops.py): traced under a mesh
with a "pp" axis it runs the GPipe ppermute schedule (parallel/pipeline.py);
single-device it applies the layers sequentially — mathematically identical,
so programs are portable across places.
"""

from __future__ import annotations

from .registry import register_op


@register_op("gpipe_mlp_stack")
def gpipe_mlp_stack_op(ctx):
    from ..parallel import pipeline as pl
    from ..parallel import spmd

    x = ctx.input("X")            # [N, D]
    w = ctx.input("W")            # [L, D, D]
    b = ctx.input("B")            # [L, D]
    act = ctx.attr("act", "relu")
    pp_axis = ctx.attr("pp_axis", "pp")
    n_micro = int(ctx.attr("n_microbatches", 4))

    mesh = spmd.active_mesh()
    n_layers = w.shape[0]
    if mesh is not None and pp_axis in mesh.axis_names \
            and mesh.shape[pp_axis] > 1 \
            and n_layers % mesh.shape[pp_axis] == 0:
        s = mesh.shape[pp_axis]
        per = n_layers // s
        params = (w.reshape((s, per) + w.shape[1:]),
                  b.reshape((s, per) + b.shape[1:]))
        out = pl.gpipe(pl.mlp_stage_fn(act), params, x, mesh, pp_axis,
                       n_micro)
    else:
        out = pl.sequential_stack(w, b, x, act)
    return {"Out": out}
