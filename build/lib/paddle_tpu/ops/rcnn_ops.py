"""Faster-RCNN training ops (ref: detection/generate_proposals_op.cc,
rpn_target_assign_op.cc, generate_proposal_labels_op.cc,
detection_map_op.*).

All four are CPU-pinned in the reference (data-dependent output counts,
random sampling); here they are EAGER host ops (executor two-tier fallback)
operating in numpy — the surrounding network stays jitted as segments.
"""

from __future__ import annotations

import numpy as np

from .registry import register_op

LOG_MAX_RATIO = float(np.log(1000.0 / 16.0))


def _np_iou(a, b):
    """Pure-numpy IoU (+1 widths) — these eager host ops call it inside
    per-box NMS loops, where a JAX round-trip per call would cost ~ms of
    dispatch each (same math as detection_ops.iou_matrix)."""
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    area_a = (a[:, 2] - a[:, 0] + 1) * (a[:, 3] - a[:, 1] + 1)
    area_b = (b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1)
    iw = np.maximum(np.minimum(a[:, None, 2], b[None, :, 2]) -
                    np.maximum(a[:, None, 0], b[None, :, 0]) + 1, 0)
    ih = np.maximum(np.minimum(a[:, None, 3], b[None, :, 3]) -
                    np.maximum(a[:, None, 1], b[None, :, 1]) + 1, 0)
    inter = iw * ih
    union = area_a[:, None] + area_b[None, :] - inter
    return np.where(union > 0, inter / union, 0.0)


def _decode_anchors(anchors, deltas, variances):
    """ref generate_proposals_op.cc BoxCoder (+1 box widths, clipped exp)."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    if variances is not None:
        dx, dy = variances[:, 0] * deltas[:, 0], variances[:, 1] * deltas[:, 1]
        dw = np.minimum(variances[:, 2] * deltas[:, 2], LOG_MAX_RATIO)
        dh = np.minimum(variances[:, 3] * deltas[:, 3], LOG_MAX_RATIO)
    else:
        dx, dy = deltas[:, 0], deltas[:, 1]
        dw = np.minimum(deltas[:, 2], LOG_MAX_RATIO)
        dh = np.minimum(deltas[:, 3], LOG_MAX_RATIO)
    cx = dx * aw + acx
    cy = dy * ah + acy
    w = np.exp(dw) * aw
    h = np.exp(dh) * ah
    return np.stack([cx - w / 2, cy - h / 2,
                     cx + w / 2 - 1, cy + h / 2 - 1], axis=1)


def _nms_plain(boxes, scores, thresh, top_n, eta=1.0):
    order = np.argsort(-scores)
    keep = []
    adaptive = thresh
    while order.size and (top_n < 0 or len(keep) < top_n):
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        ious = _np_iou(boxes[i:i + 1], boxes[order[1:]])[0]
        order = order[1:][ious <= adaptive]
        if eta < 1 and adaptive > 0.5:
            adaptive *= eta
    return np.asarray(keep, np.int64)


@register_op("generate_proposals", no_grad_inputs=("Scores", "BboxDeltas",
                                                   "ImInfo", "Anchors",
                                                   "Variances"))
def generate_proposals(ctx):
    """RPN head -> proposal boxes (ref generate_proposals_op.cc:
    decode -> clip to image -> filter tiny -> top-pre_nms -> NMS ->
    top-post_nms, per image, LoD output)."""
    scores = np.asarray(ctx.input("Scores"))        # [N, A, H, W]
    deltas = np.asarray(ctx.input("BboxDeltas"))    # [N, 4A, H, W]
    im_info = np.asarray(ctx.input("ImInfo"))       # [N, 3] (h, w, scale)
    anchors = np.asarray(ctx.input("Anchors")).reshape(-1, 4)
    variances = ctx.input("Variances")
    variances = np.asarray(variances).reshape(-1, 4) \
        if variances is not None else None
    pre_n = ctx.attr("pre_nms_topN", 6000)
    post_n = ctx.attr("post_nms_topN", 1000)
    nms_thresh = ctx.attr("nms_thresh", 0.5)
    min_size = ctx.attr("min_size", 0.1)
    eta = ctx.attr("eta", 1.0)

    n = scores.shape[0]
    rois, probs, lod = [], [], [0]
    for i in range(n):
        sc = scores[i].transpose(1, 2, 0).reshape(-1)        # HWA order
        dl = deltas[i].reshape(-1, 4, *deltas.shape[2:]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-sc)
        if pre_n > 0:
            order = order[:pre_n]
        props = _decode_anchors(anchors[order], dl[order],
                                variances[order] if variances is not None
                                else None)
        h, w = im_info[i, 0], im_info[i, 1]
        props[:, 0::2] = np.clip(props[:, 0::2], 0, w - 1)
        props[:, 1::2] = np.clip(props[:, 1::2], 0, h - 1)
        ws = props[:, 2] - props[:, 0] + 1
        hs = props[:, 3] - props[:, 1] + 1
        ms = min_size * im_info[i, 2]
        keep = (ws >= ms) & (hs >= ms)
        props, sc_k = props[keep], sc[order][keep]
        if len(props):
            kept = _nms_plain(props, sc_k, nms_thresh, post_n, eta)
            props, sc_k = props[kept], sc_k[kept]
        rois.append(props)
        probs.append(sc_k)
        lod.append(lod[-1] + len(props))
    rois = np.concatenate(rois, 0).astype(np.float32) if lod[-1] else \
        np.zeros((1, 4), np.float32)
    probs = np.concatenate(probs, 0).astype(np.float32).reshape(-1, 1) \
        if lod[-1] else np.zeros((1, 1), np.float32)
    the_lod = [(tuple(lod),)]
    return {"RpnRois": rois, "RpnRoiProbs": probs,
            "RpnRois@LOD": the_lod, "RpnRoiProbs@LOD": the_lod}


_SAMPLER_CALLS = [0]


def _op_rng(ctx):
    """Fresh randomness per execution (ref rpn_target_assign_op.cc:346
    seeds from std::random_device each run).  An explicit nonzero ``seed``
    attr gives a reproducible-but-still-varying stream (seed + call#)."""
    _SAMPLER_CALLS[0] += 1
    seed = ctx.attr("seed", 0)
    if seed:
        return np.random.RandomState(int(seed) + _SAMPLER_CALLS[0])
    return np.random.RandomState()  # OS entropy


def _segments(lod, total):
    """Per-image (start, end) pairs from a LoD, or one segment."""
    if lod:
        off = lod[-1]
        return [(int(off[i]), int(off[i + 1])) for i in range(len(off) - 1)]
    return [(0, total)]


def _drop_crowd(gt, crowd_flags, seg):
    s, e = seg
    g = gt[s:e]
    if crowd_flags is None:
        return g
    c = np.asarray(crowd_flags).reshape(-1)[s:e].astype(bool)
    return g[~c]


@register_op("rpn_target_assign",
             no_grad_inputs=("Anchor", "GtBoxes", "IsCrowd", "ImInfo",
                             "DistMat"))
def rpn_target_assign(ctx):
    """Sample anchors for RPN training (ref rpn_target_assign_op.cc):
    per IMAGE (GtBoxes LoD, ref :327 batch loop; crowd boxes excluded,
    ref generate_proposal_labels_op.cc:111): positives = best-per-gt +
    IoU >= pos_thresh; negatives = IoU < neg_thresh; subsample to
    rpn_batch_size_per_im with fg_fraction.  Output indices are flat into
    [n_images * n_anchors]."""
    anchors = np.asarray(ctx.input("Anchor")).reshape(-1, 4)
    gt_all = np.asarray(ctx.input("GtBoxes")).reshape(-1, 4)
    crowd = ctx.input("IsCrowd")
    batch = ctx.attr("rpn_batch_size_per_im", 256)
    fg_frac = ctx.attr("rpn_fg_fraction", 0.5)
    pos_t = ctx.attr("rpn_positive_overlap", 0.7)
    neg_t = ctx.attr("rpn_negative_overlap", 0.3)
    use_random = ctx.attr("use_random", True)
    rng = _op_rng(ctx)
    segs = _segments(ctx.in_lod("GtBoxes"), len(gt_all))
    n_anchor = len(anchors)

    locs, scores, slabels, tbs = [], [], [], []
    for i, seg in enumerate(segs):
        gt = _drop_crowd(gt_all, crowd, seg)
        fg_idx, bg_idx, tb = _rpn_assign_one(
            anchors, gt, batch, fg_frac, pos_t, neg_t, use_random, rng)
        locs.append(fg_idx + i * n_anchor)
        scores.append(np.concatenate([fg_idx, bg_idx]) + i * n_anchor)
        slabels.append(np.concatenate([np.ones(len(fg_idx)),
                                       np.zeros(len(bg_idx))]))
        tbs.append(tb)
    return {"LocationIndex": np.concatenate(locs).astype(np.int64),
            "ScoreIndex": np.concatenate(scores).astype(np.int64),
            "TargetLabel": np.concatenate(slabels)
            .astype(np.int64).reshape(-1, 1),
            "TargetBBox": np.concatenate(tbs).astype(np.float32)}


def _rpn_assign_one(anchors, gt, batch, fg_frac, pos_t, neg_t, use_random,
                    rng):
    iou = _np_iou(gt, anchors) if len(gt) else \
        np.zeros((0, len(anchors)), np.float32)
    max_per_anchor = iou.max(0) if len(gt) else \
        np.zeros(len(anchors), np.float32)
    labels = np.full(len(anchors), -1, np.int32)
    # negatives FIRST so the per-gt best-anchor guarantee overrides them
    # (ref rpn_target_assign_op.cc: every gt keeps >=1 positive anchor
    # even when its best IoU falls below the negative threshold)
    labels[max_per_anchor < neg_t] = 0
    if len(gt):
        labels[max_per_anchor >= pos_t] = 1
        best_anchor = iou.argmax(1)
        labels[best_anchor] = 1

    fg_idx = np.where(labels == 1)[0]
    bg_idx = np.where(labels == 0)[0]
    n_fg = int(batch * fg_frac)
    if len(fg_idx) > n_fg:
        drop = (rng.permutation(fg_idx)[n_fg:] if use_random
                else fg_idx[n_fg:])
        labels[drop] = -1
        fg_idx = np.where(labels == 1)[0]
    n_bg = batch - len(fg_idx)
    if len(bg_idx) > n_bg:
        drop = (rng.permutation(bg_idx)[n_bg:] if use_random
                else bg_idx[n_bg:])
        labels[drop] = -1
        bg_idx = np.where(labels == 0)[0]

    if len(gt) and len(fg_idx):
        match_gt = iou[:, fg_idx].argmax(0)
        tgt = gt[match_gt]
        a = anchors[fg_idx]
        aw = a[:, 2] - a[:, 0] + 1.0
        ah = a[:, 3] - a[:, 1] + 1.0
        acx = a[:, 0] + 0.5 * aw
        acy = a[:, 1] + 0.5 * ah
        gw = tgt[:, 2] - tgt[:, 0] + 1.0
        gh = tgt[:, 3] - tgt[:, 1] + 1.0
        gcx = tgt[:, 0] + 0.5 * gw
        gcy = tgt[:, 1] + 0.5 * gh
        tb = np.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                       np.log(gw / aw), np.log(gh / ah)], 1)
    else:
        tb = np.zeros((0, 4), np.float32)
    return fg_idx, bg_idx, tb


@register_op("generate_proposal_labels",
             no_grad_inputs=("RpnRois", "GtClasses", "IsCrowd", "GtBoxes",
                             "ImInfo"))
def generate_proposal_labels(ctx):
    """Sample RoIs + assign classification/regression targets for the
    RCNN head, per IMAGE over the RpnRois/GtBoxes LoDs with crowd gt
    excluded (ref generate_proposal_labels_op.cc SampleRoisForOneImage,
    crowd filter :111)."""
    rois_all = np.asarray(ctx.input("RpnRois")).reshape(-1, 4)
    gt_cls_all = np.asarray(ctx.input("GtClasses")).reshape(-1) \
        .astype(np.int64)
    gt_all = np.asarray(ctx.input("GtBoxes")).reshape(-1, 4)
    crowd = ctx.input("IsCrowd")
    attrs = dict(
        batch=ctx.attr("batch_size_per_im", 256),
        fg_frac=ctx.attr("fg_fraction", 0.25),
        fg_t=ctx.attr("fg_thresh", 0.5),
        bg_hi=ctx.attr("bg_thresh_hi", 0.5),
        bg_lo=ctx.attr("bg_thresh_lo", 0.0),
        n_class=ctx.attr("class_nums", 81),
        use_random=ctx.attr("use_random", True))
    rng = _op_rng(ctx)
    roi_segs = _segments(ctx.in_lod("RpnRois"), len(rois_all))
    gt_segs = _segments(ctx.in_lod("GtBoxes"), len(gt_all))
    if len(gt_segs) != len(roi_segs):
        gt_segs = [(0, len(gt_all))] * len(roi_segs)

    outs = {"rois": [], "labels": [], "tgt": [], "w_in": []}
    lod = [0]
    for seg_r, seg_g in zip(roi_segs, gt_segs):
        rois = rois_all[seg_r[0]: seg_r[1]]
        gt = _drop_crowd(gt_all, crowd, seg_g)
        keep = np.ones(seg_g[1] - seg_g[0], bool)
        if crowd is not None:
            keep = ~np.asarray(crowd).reshape(-1)[seg_g[0]: seg_g[1]] \
                .astype(bool)
        gt_cls = gt_cls_all[seg_g[0]: seg_g[1]][keep]
        r, l, t, w = _sample_rois_one(rois, gt, gt_cls, rng, **attrs)
        outs["rois"].append(r)
        outs["labels"].append(l)
        outs["tgt"].append(t)
        outs["w_in"].append(w)
        lod.append(lod[-1] + len(r))
    out_rois = np.concatenate(outs["rois"], 0).astype(np.float32)
    labels = np.concatenate(outs["labels"], 0)
    tgt = np.concatenate(outs["tgt"], 0)
    w_in = np.concatenate(outs["w_in"], 0)
    return {"Rois": out_rois, "LabelsInt32": labels.astype(np.int32),
            "BboxTargets": tgt, "BboxInsideWeights": w_in,
            "BboxOutsideWeights": (w_in > 0).astype(np.float32),
            "Rois@LOD": [(tuple(lod),)]}


def _sample_rois_one(rois, gt, gt_cls, rng, batch, fg_frac, fg_t, bg_hi,
                     bg_lo, n_class, use_random):
    cand = np.concatenate([rois, gt], 0) if len(gt) else rois
    iou = _np_iou(gt, cand) if len(gt) else \
        np.zeros((0, len(cand)), np.float32)
    max_iou = iou.max(0) if len(gt) else np.zeros(len(cand))
    gt_of = iou.argmax(0) if len(gt) else np.zeros(len(cand), np.int64)
    fg = np.where(max_iou >= fg_t)[0]
    bg = np.where((max_iou < bg_hi) & (max_iou >= bg_lo))[0]
    n_fg = min(int(batch * fg_frac), len(fg))
    n_bg = min(batch - n_fg, len(bg))
    if use_random:
        fg = rng.permutation(fg)[:n_fg]
        bg = rng.permutation(bg)[:n_bg]
    else:
        fg, bg = fg[:n_fg], bg[:n_bg]
    sel = np.concatenate([fg, bg])
    out_rois = cand[sel].astype(np.float32)
    labels = np.concatenate([
        gt_cls[gt_of[fg]] if len(gt) else np.zeros(len(fg), np.int64),
        np.zeros(len(bg), np.int64)]).astype(np.int64).reshape(-1, 1)

    tgt = np.zeros((len(sel), 4 * n_class), np.float32)
    w_in = np.zeros_like(tgt)
    if len(gt):
        g = gt[gt_of[fg]]
        a = cand[fg]
        aw = a[:, 2] - a[:, 0] + 1.0
        ah = a[:, 3] - a[:, 1] + 1.0
        acx = a[:, 0] + 0.5 * aw
        acy = a[:, 1] + 0.5 * ah
        gw = g[:, 2] - g[:, 0] + 1.0
        gh = g[:, 3] - g[:, 1] + 1.0
        deltas = np.stack([(g[:, 0] + 0.5 * gw - acx) / aw,
                           (g[:, 1] + 0.5 * gh - acy) / ah,
                           np.log(gw / aw), np.log(gh / ah)], 1)
        for j, (row, cls) in enumerate(zip(deltas, labels[:len(fg), 0])):
            tgt[j, 4 * cls: 4 * cls + 4] = row
            w_in[j, 4 * cls: 4 * cls + 4] = 1.0
    return out_rois, labels, tgt, w_in


@register_op("detection_map",
             no_grad_inputs=("DetectRes", "Label", "HasState", "PosCount",
                             "TruePos", "FalsePos"))
def detection_map(ctx):
    """Single-batch mAP (ref detection_map_op.h: 11-point or integral AP
    over per-class ranked detections vs labeled boxes)."""
    det = np.asarray(ctx.input("DetectRes"))    # [M, 6] label,score,box
    gt = np.asarray(ctx.input("Label"))         # [N, 6] or [N, 5]
    overlap_t = ctx.attr("overlap_threshold", 0.5)
    ap_type = ctx.attr("ap_type", "integral")
    background = ctx.attr("background_label", 0)
    det_lod = ctx.in_lod("DetectRes")
    gt_lod = ctx.in_lod("Label")
    doff = det_lod[-1] if det_lod else (0, len(det))
    goff = gt_lod[-1] if gt_lod else (0, len(gt))

    # per class: ranked (score, tp) pairs + positive count; SEEDED from the
    # accumulator-state inputs when chaining batches (ref detection_map_op.h
    # GetInputPos: PosCount [C, 1], True/FalsePos rows of (class, score,
    # flag) — our dense rendering of its LoD form)
    tps, npos = {}, {}
    pos_count = ctx.input("PosCount")
    true_pos = ctx.input("TruePos")
    if pos_count is not None and np.asarray(pos_count).size:
        for c, n in np.asarray(pos_count).reshape(-1, 2):
            npos[int(c)] = int(n)
    if true_pos is not None and np.asarray(true_pos).size:
        for c, score, flag in np.asarray(true_pos).reshape(-1, 3):
            tps.setdefault(int(c), []).append((float(score), int(flag)))
    for i in range(len(doff) - 1):
        d = det[int(doff[i]): int(doff[i + 1])]
        g = gt[int(goff[i]): int(goff[i + 1])]
        g_lab = g[:, 0].astype(int)
        g_box = g[:, -4:]
        for c in np.unique(g_lab):
            if c == background:  # ref detection_map_op.h skips background
                continue
            npos[c] = npos.get(c, 0) + int((g_lab == c).sum())
        used = np.zeros(len(g), bool)
        order = np.argsort(-d[:, 1])
        for j in order:
            c = int(d[j, 0])
            if c == background:
                continue
            box = d[j, 2:6]
            cand = np.where((g_lab == c) & ~used)[0]
            tp = 0
            if len(cand):
                ious = _np_iou(box[None], g_box[cand])[0]
                k = ious.argmax()
                if ious[k] >= overlap_t:
                    used[cand[k]] = True
                    tp = 1
            tps.setdefault(c, []).append((d[j, 1], tp))

    aps = []
    for c, pairs in tps.items():
        if npos.get(c, 0) == 0:
            continue
        pairs.sort(key=lambda t: -t[0])
        tp_cum = np.cumsum([t for _, t in pairs])
        fp_cum = np.cumsum([1 - t for _, t in pairs])
        recall = tp_cum / npos[c]
        precision = tp_cum / np.maximum(tp_cum + fp_cum, 1e-12)
        if ap_type == "11point":
            ap = float(np.mean([precision[recall >= r].max()
                                if (recall >= r).any() else 0.0
                                for r in np.arange(0, 1.01, 0.1)]))
        else:  # integral
            ap = 0.0
            prev_r = 0.0
            for p, r in zip(precision, recall):
                ap += p * (r - prev_r)
                prev_r = r
        aps.append(ap)
    for c, n in npos.items():
        if c not in tps:
            aps.append(0.0)
    m_ap = float(np.mean(aps)) if aps else 0.0
    # emit chainable accumulators: feed AccumPosCount/AccumTruePos back as
    # PosCount/TruePos on the next batch for dataset-level mAP
    acc_pos = np.asarray([[c, n] for c, n in sorted(npos.items())],
                         np.float32).reshape(-1, 2) \
        if npos else np.zeros((0, 2), np.float32)
    acc_tp = np.asarray([[c, s, f] for c, pairs in sorted(tps.items())
                         for s, f in pairs], np.float32).reshape(-1, 3) \
        if tps else np.zeros((0, 3), np.float32)
    return {"MAP": np.asarray([m_ap], np.float32),
            "AccumPosCount": acc_pos,
            "AccumTruePos": acc_tp,
            "AccumFalsePos": np.zeros((0, 3), np.float32)}
