"""Mixture-of-experts with expert parallelism over an "ep" mesh axis.

A capability beyond the reference (SURVEY.md §2.6: MoE/EP "Absent" — its
nearest analogue is the pserver-sharded distributed lookup table,
ref distribute_transpiler.py:379-382).  Here routing is the GShard/Switch
einsum-dispatch formulation: a differentiable dense dispatch/combine pair of
[N, E, C] tensors instead of data-dependent gather/scatter, so the whole
layer stays a static-shape XLA program.  Under GSPMD with the expert
dimension of the weights sharded on "ep", the dispatch einsum lowers to the
all-to-all over ICI that a hand-written MPI implementation would issue —
no manual collectives needed.

Dropped-token semantics: tokens beyond an expert's capacity contribute zero
to the layer output (callers add a residual connection, as all MoE
transformer blocks do).
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def moe_capacity(n_tokens: int, num_experts: int, top_k: int,
                 capacity_factor: float) -> int:
    return max(1, int(math.ceil(n_tokens * top_k / num_experts
                                * capacity_factor)))


def top_k_gating(x, gate_w, top_k: int, capacity_factor: float
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Compute (combine [N,E,C], dispatch [N,E,C], aux_loss scalar).

    x: [N, D] tokens; gate_w: [D, E].  Routing follows Switch/GShard:
    softmax gate, top-k experts per token, per-expert capacity with
    first-come-first-served overflow dropping, gate values renormalized
    over the chosen k.  aux_loss is the Switch load-balancing loss
    E * sum_e(frac_tokens_e * mean_prob_e), which is 1.0 at perfect
    balance.
    """
    n, _ = x.shape
    e = gate_w.shape[-1]
    cap = moe_capacity(n, e, top_k, capacity_factor)
    # gate math in fp32: tiny logit differences decide routing, and bf16
    # softmax would make single- vs multi-chip routing diverge
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)  # [N, E]
    gate_vals, gate_idx = lax.top_k(probs, top_k)  # [N, k]
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    combine = jnp.zeros((n, e, cap), jnp.float32)
    counts = jnp.zeros((e,), jnp.float32)
    for j in range(top_k):
        oh = jax.nn.one_hot(gate_idx[:, j], e, dtype=jnp.float32)  # [N, E]
        # position this token would take in each expert's buffer
        pos = counts[None, :] + jnp.cumsum(oh, axis=0) - oh  # [N, E]
        keep = oh * (pos < cap)  # drop overflow
        counts = counts + jnp.sum(keep, axis=0)
        slot = jnp.sum(pos * keep, axis=-1).astype(jnp.int32)  # [N]
        slot_oh = jax.nn.one_hot(slot, cap, dtype=jnp.float32)  # [N, C]
        combine = combine + (gate_vals[:, j, None, None]
                             * keep[:, :, None] * slot_oh[:, None, :])
    dispatch = (combine > 0).astype(jnp.float32)

    frac_routed = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], e,
                                          dtype=jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = e * jnp.sum(frac_routed * mean_prob)
    return combine, dispatch, aux_loss


def moe_ffn(x, gate_w, w1, b1, w2, b2, top_k: int = 2,
            capacity_factor: float = 1.25, activation: str = "relu"
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert feed-forward over routed tokens.

    x: [..., D]; gate_w: [D, E]; w1: [E, D, H]; b1: [E, H]; w2: [E, H, D];
    b2: [E, D].  Returns (y [..., D], aux_loss scalar).  All expert math
    happens at [E, C, ·] — with w1/w2 sharded on the "ep" axis GSPMD keeps
    each expert's tokens and FLOPs on its own devices.
    """
    orig_shape = x.shape
    d = orig_shape[-1]
    xt = x.reshape((-1, d))
    combine, dispatch, aux = top_k_gating(xt, gate_w, top_k, capacity_factor)
    dtype = x.dtype
    from .pipeline import _apply_act

    expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(dtype), xt)
    h = _apply_act(jnp.einsum("ecd,edh->ech", expert_in, w1)
                   + b1[:, None, :], activation)
    expert_out = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
    y = jnp.einsum("nec,ecd->nd", combine.astype(dtype), expert_out)
    return y.reshape(orig_shape), aux.astype(dtype)
