"""Async-parameter-server replacement: local SGD with periodic averaging.

The reference's async mode (ref: operators/distributed/listen_and_serv_op
.cc:213 RunAsyncLoop) lets every trainer push gradients and pull parameters
without a barrier — trading staleness for throughput.  A literal port is
meaningless under SPMD (there is no parameter-server process), but the
same trade has a TPU-native form: **local SGD** — each process trains its
OWN parameter copy with zero per-step communication, and every
``sync_period`` steps the copies average across processes (one collective
round over DCN).  Staleness is bounded by the period instead of unbounded
like the reference's async loop — strictly better-behaved, same
throughput motivation.

Exactness anchor: with plain SGD and sync_period=1, averaging the
post-step parameter copies equals averaging the gradients —
w_i = w - lr*g_i  =>  mean_i(w_i) = w - lr*mean_i(g_i) — i.e. one-step
local SGD IS synchronous data parallelism, which gives the oracle test a
bit-exact target.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["AsyncLocalSGDTrainer"]


class AsyncLocalSGDTrainer:
    """Wrap a single-process Executor train loop with periodic cross-
    process parameter averaging (jax.distributed must be initialized, e.g.
    via DistributeTranspiler.transpile(sync_mode=False))."""

    def __init__(self, program, loss_name: str, sync_period: int = 16,
                 place=None, scope=None, average_accumulators: bool = True):
        from ..fluid import CPUPlace, Executor, TPUPlace, core
        from ..fluid.executor import global_scope

        self.program = program
        self.loss_name = loss_name
        self.sync_period = max(1, int(sync_period))
        self.scope = scope or global_scope()
        if place is None:
            place = TPUPlace() if core.is_compiled_with_tpu() else CPUPlace()
        self.exe = Executor(place)
        self.average_accumulators = average_accumulators
        self._step = 0
        # every persistable float the optimizer touches averages; params
        # always, accumulators by option (momentum averaging is standard
        # local-SGD practice), integer state (steps) never
        self._avg_names = self._averaged_names()

    def _averaged_names(self) -> List[str]:
        from ..fluid.framework import Parameter

        gb = self.program.global_block()
        names = []
        acc_owner = getattr(self.program, "_accumulator_owner", {})
        for name, v in gb.vars.items():
            if isinstance(v, Parameter) and getattr(v, "trainable", True):
                names.append(name)
            elif self.average_accumulators and name in acc_owner:
                if v.dtype is None or "int" not in str(v.dtype):
                    names.append(name)
        return sorted(names)

    def step(self, feed: Dict[str, np.ndarray],
             fetch_list: Optional[list] = None):
        """One LOCAL train step (no communication); triggers an averaging
        round every sync_period steps."""
        out = self.exe.run(self.program, feed=feed,
                           fetch_list=fetch_list
                           if fetch_list is not None else [self.loss_name],
                           scope=self.scope)
        self._step += 1
        if self._step % self.sync_period == 0:
            self.sync()
        return out

    def sync(self):
        """Average the parameter copies across processes (one allgather
        round over DCN; a no-op single-process)."""
        from . import multihost as mh

        if mh.process_count() <= 1:
            return
        from jax.experimental import multihost_utils as mhu

        for name in self._avg_names:
            val = np.asarray(self.scope.get(name))
            stacked = np.asarray(mhu.process_allgather(val))
            self.scope.set(name, stacked.mean(axis=0).astype(val.dtype))
