"""Mesh construction (ref analogue: platform/nccl_helper.h NCCLContextMap —
rank math over trainers × local GPUs becomes an N-D device mesh)."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def local_device_count(platform=None) -> int:
    try:
        return len(jax.devices(platform)) if platform else len(jax.devices())
    except RuntimeError:
        return 0


def make_mesh(n_devices=None, tp=1, axis_names=("dp", "mp")) -> Mesh:
    """Build a (dp × tp) mesh over the first n_devices devices.

    tp ("mp" axis) shards model weights; dp shards the batch.  On a real pod
    the mesh should map tp to the innermost ICI dimension — jax device order
    already enumerates ICI-adjacent chips first.
    """
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, only {len(devs)} visible")
    if n % tp != 0:
        raise ValueError(f"n_devices={n} not divisible by tp={tp}")
    arr = np.array(devs[:n]).reshape(n // tp, tp)
    return Mesh(arr, axis_names)


def make_mesh_nd(**axes) -> Mesh:
    """N-D mesh from named axis sizes, e.g. ``make_mesh_nd(dp=2, mp=2,
    pp=2)``.  Axis order = keyword order (python dicts preserve it); later
    axes map to faster-varying device indices, i.e. the innermost/most-
    ICI-adjacent dimension — put the most communication-hungry axis last."""
    names = tuple(axes)
    sizes = tuple(int(s) for s in axes.values())
    n = int(np.prod(sizes))
    devs = jax.devices()
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, only {len(devs)} visible")
    arr = np.array(devs[:n]).reshape(sizes)
    return Mesh(arr, names)
