"""v2 optimizers (ref: python/paddle/v2/optimizer.py — Momentum :183,
Adam :220, AdaGrad, RMSProp; each wrapped the swig ParameterUpdater).
Here each builds the matching Fluid optimizer at SGD-construction time."""

from __future__ import annotations

from ..fluid import optimizer as fluid_opt, regularizer as fluid_reg

__all__ = ["Optimizer", "Momentum", "Adam", "AdaGrad", "RMSProp"]


def _reg(regularization):
    if regularization is None:
        return None
    if isinstance(regularization, fluid_reg.WeightDecayRegularizer):
        return regularization
    # trainer_config_helpers.L2Regularization marker
    build = getattr(regularization, "build", None)
    return build() if build else None


class Optimizer:
    def __init__(self, learning_rate=1e-3, regularization=None, **kwargs):
        self.learning_rate = learning_rate
        self.regularization = _reg(regularization)

    def build(self):
        raise NotImplementedError


class Momentum(Optimizer):
    def __init__(self, momentum=0.9, sparse=False, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def build(self):
        return fluid_opt.Momentum(learning_rate=self.learning_rate,
                                  momentum=self.momentum,
                                  regularization=self.regularization)


class Adam(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def build(self):
        return fluid_opt.Adam(learning_rate=self.learning_rate,
                              beta1=self.beta1, beta2=self.beta2,
                              epsilon=self.epsilon,
                              regularization=self.regularization)


class AdaGrad(Optimizer):
    def build(self):
        return fluid_opt.Adagrad(learning_rate=self.learning_rate,
                                 regularization=self.regularization)


class RMSProp(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def build(self):
        return fluid_opt.RMSProp(learning_rate=self.learning_rate,
                                 rho=self.rho, epsilon=self.epsilon,
                                 regularization=self.regularization)
