"""v2 pooling namespace (ref: python/paddle/v2/pooling.py)."""

from ..trainer_config_helpers import (AvgPooling as Avg, MaxPooling as Max)

__all__ = ["Max", "Avg"]
