"""v2 activation namespace (ref: python/paddle/v2/activation.py — renames
trainer_config_helpers activations: Relu == ReluActivation etc.)."""

from ..trainer_config_helpers import (LinearActivation as Linear,
                                      ReluActivation as Relu,
                                      SigmoidActivation as Sigmoid,
                                      SoftmaxActivation as Softmax,
                                      TanhActivation as Tanh)

__all__ = ["Linear", "Relu", "Sigmoid", "Softmax", "Tanh"]
