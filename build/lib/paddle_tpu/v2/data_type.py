"""v2 data-type declarations (ref: python/paddle/v2/data_type.py — thin
wrappers over trainer.PyDataProvider2 input types)."""

from __future__ import annotations


class InputType:
    def __init__(self, dim, dtype, seq=False):
        self.dim = dim
        self.dtype = dtype
        self.seq = seq


def dense_vector(dim):
    return InputType(dim, "float32")


def dense_array(dim):
    return InputType(dim, "float32")


def integer_value(value_range):
    return InputType(value_range, "int64")


def sparse_binary_vector(dim):
    return InputType(dim, "float32")


def integer_value_sequence(value_range):
    return InputType(value_range, "int64", seq=True)


def dense_vector_sequence(dim):
    return InputType(dim, "float32", seq=True)
