"""v2 training events (ref: python/paddle/v2/event.py — BeginPass :58,
EndPass :67, BeginIteration :80, EndIteration :89, TestResult :48).
Fired by trainer.SGD.train around every batch/pass."""

from __future__ import annotations

__all__ = ["BeginPass", "EndPass", "BeginIteration", "EndIteration",
           "TestResult"]


class WithMetric:
    def __init__(self, metrics=None):
        self.metrics = dict(metrics or {})


class TestResult(WithMetric):
    def __init__(self, cost, metrics=None):
        super().__init__(metrics)
        self.cost = cost


class BeginPass:
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id, metrics=None):
        super().__init__(metrics)
        self.pass_id = pass_id


class BeginIteration:
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration(WithMetric):
    def __init__(self, pass_id, batch_id, cost, metrics=None):
        super().__init__(metrics)
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
