"""v2 layer namespace (ref: python/paddle/v2/layer.py — the v2 book API:
``paddle.layer.data/fc/conv/...``), lowered onto Fluid like
trainer_config_helpers (one substrate, both v2 front ends)."""

from __future__ import annotations

from ..fluid import layers as _fl
from ..trainer_config_helpers import (_act_name, _to_nchw, addto_layer,
                                      batch_norm_layer, classification_cost,
                                      cross_entropy, dropout_layer,
                                      embedding_layer, fc_layer,
                                      img_conv_layer, img_pool_layer)

__all__ = ["data", "fc", "embedding", "img_conv", "img_pool", "batch_norm",
           "addto", "dropout", "cross_entropy_cost", "classification_cost",
           "mse_cost"]


def data(name, type):
    """paddle.v2.layer.data(name=..., type=paddle.data_type.X(dim))."""
    v = _fl.data(name=name, shape=[int(type.dim)], dtype=type.dtype)
    if type.dtype == "int64":
        # classification labels / token ids arrive as [N, 1] ids
        v.shape = (v.shape[0], 1)
    return v


fc = fc_layer
embedding = embedding_layer
img_conv = img_conv_layer
img_pool = img_pool_layer
batch_norm = batch_norm_layer
addto = addto_layer
dropout = dropout_layer
cross_entropy_cost = cross_entropy


def mse_cost(input, label, name=None):
    return _fl.mean(_fl.square_error_cost(input=input, label=label))
