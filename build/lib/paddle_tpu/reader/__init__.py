"""Composable data readers (ref: python/paddle/reader/decorator.py)."""

from .decorator import (buffered, cache, chain, compose, firstn, map_readers,
                        shuffle, xmap_readers)

__all__ = ["buffered", "cache", "chain", "compose", "firstn", "map_readers",
           "shuffle", "xmap_readers", "batch"]


def batch(reader, batch_size, drop_last=False):
    """Group sample reader into a minibatch reader (ref: python/paddle/batch.py)."""

    def batch_reader():
        r = reader()
        b = []
        for instance in r:
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
