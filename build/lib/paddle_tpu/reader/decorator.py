"""Reader decorators (ref: python/paddle/reader/decorator.py:36-443)."""

from __future__ import annotations

import itertools
import random
from queue import Queue
from threading import Thread

__all__ = ["PipeReader", "map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "cache"]


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in zip_longest_check(*rs):
                yield sum(list(map(make_tuple, outputs)), ())

    def zip_longest_check(*iters):
        sentinel = object()
        for row in itertools.zip_longest(*iters, fillvalue=sentinel):
            if sentinel in row:
                raise ComposeNotAligned("readers have different lengths")
            yield row

    return reader


def buffered(reader, size):
    class EndSignal:
        pass

    end = EndSignal()

    def read_worker(r, q):
        for d in r:
            q.put(d)
        q.put(end)

    def data_reader():
        r = reader()
        q = Queue(maxsize=size)
        t = Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while e is not end:
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel-map a reader with worker threads (ref: decorator.py:243)."""
    end = object()

    def data_reader():
        in_q = Queue(buffer_size)
        out_q = Queue(buffer_size)

        def feed():
            for sample in reader():
                in_q.put(sample)
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                sample = in_q.get()
                if sample is end:
                    out_q.put(end)
                    return
                out_q.put(mapper(sample))

        feeder = Thread(target=feed)
        feeder.daemon = True
        feeder.start()
        workers = []
        for _ in range(process_num):
            w = Thread(target=work)
            w.daemon = True
            w.start()
            workers.append(w)
        finished = 0
        while finished < process_num:
            sample = out_q.get()
            if sample is end:
                finished += 1
            else:
                yield sample

    return data_reader


def cache(reader):
    all_data = []

    def cache_reader():
        if not all_data:
            all_data.extend(reader())
        for d in all_data:
            yield d

    return cache_reader


class PipeReader:
    """Stream records from a shell command's stdout (ref:
    python/paddle/reader/decorator.py:438 — used to read sharded datasets
    from `hadoop fs -cat` style pipes).  ``get_line`` yields decoded lines
    split on ``line_break``; callers parse each into a sample."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        if not isinstance(command, str):
            raise TypeError("PipeReader command must be a string")
        import subprocess

        self.command = command
        self.bufsize = bufsize
        self.file_type = file_type
        self.process = subprocess.Popen(
            command.split(" "), bufsize=bufsize, stdout=subprocess.PIPE)
        if file_type == "gzip":
            import zlib

            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)
        elif file_type != "plain":
            raise TypeError(f"file_type {file_type} is not allowed")

    def close(self):
        if self.process.poll() is None:
            self.process.terminate()
        if self.process.stdout and not self.process.stdout.closed:
            self.process.stdout.close()
        self.process.wait()

    def get_line(self, cut_lines=True, line_break="\n"):
        import codecs
        import zlib

        # incremental decoder: a multibyte UTF-8 char split across the
        # bufsize boundary must not be dropped
        decoder = codecs.getincrementaldecoder("utf-8")("ignore")
        remained = ""
        try:
            while True:
                buff = self.process.stdout.read(self.bufsize)
                if not buff:
                    break
                if self.file_type == "gzip":
                    out = [self.dec.decompress(buff)]
                    # concatenated members (one per shard in `cat *.gz`
                    # pipes): restart the decompressor on leftover bytes —
                    # but only when they start a real member; gzip(1)
                    # tolerates trailing garbage (block padding) and so
                    # must we
                    while self.dec.eof and \
                            self.dec.unused_data.startswith(b"\x1f\x8b"):
                        rest = self.dec.unused_data
                        self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)
                        out.append(self.dec.decompress(rest))
                    buff = b"".join(out)
                decomp_buff = decoder.decode(buff)
                if not cut_lines:
                    yield decomp_buff
                    continue
                lines = (remained + decomp_buff).split(line_break)
                remained = lines.pop(-1)
                for line in lines:
                    yield line
            remained += decoder.decode(b"", final=True)
            if remained:
                yield remained
        finally:
            # consumers that stop early (firstn) must not leak the child
            self.close()
