// RecordIO: chunked record file format (native component).
//
// ref: paddle/fluid/recordio/{header,chunk,scanner,writer} — the reference's
// chunked record container (magic + compressor + CRC per chunk).  This is a
// fresh TPU-era design, not a port: 64-bit lengths, zlib (snappy is not in
// the image), and a single-pass streaming scanner.
//
// On-disk layout:
//   file   := chunk*
//   chunk  := magic(u32 = 0x50545231 "PTR1") | compressor(u32)
//           | num_records(u32) | raw_len(u64) | stored_len(u64)
//           | crc32(u32, of stored payload) | payload
//   payload (after decompression) := { rec_len(u64) | bytes }*
//
// Exposed through a C API consumed by ctypes (pybind11 is not available in
// the build image; see paddle_tpu/native/__init__.py).

#include <zlib.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50545231;  // "PTR1"

enum Compressor : uint32_t { kNone = 0, kZlib = 1 };

struct Writer {
  FILE* f = nullptr;
  uint32_t compressor = kZlib;
  size_t max_chunk_bytes = 1 << 20;
  std::vector<std::string> pending;
  size_t pending_bytes = 0;

  bool FlushChunk() {
    if (pending.empty()) return true;
    std::string raw;
    raw.reserve(pending_bytes + pending.size() * 8);
    for (auto& r : pending) {
      uint64_t len = r.size();
      raw.append(reinterpret_cast<const char*>(&len), 8);
      raw.append(r);
    }
    std::string stored;
    if (compressor == kZlib) {
      uLongf bound = compressBound(raw.size());
      stored.resize(bound);
      if (compress2(reinterpret_cast<Bytef*>(&stored[0]), &bound,
                    reinterpret_cast<const Bytef*>(raw.data()), raw.size(),
                    /*level=*/1) != Z_OK) {
        return false;
      }
      stored.resize(bound);
    } else {
      stored = raw;
    }
    uint32_t magic = kMagic, comp = compressor,
             n = static_cast<uint32_t>(pending.size());
    uint64_t raw_len = raw.size(), stored_len = stored.size();
    uint32_t crc = crc32(0L, reinterpret_cast<const Bytef*>(stored.data()),
                         stored.size());
    bool ok = fwrite(&magic, 4, 1, f) == 1 && fwrite(&comp, 4, 1, f) == 1 &&
              fwrite(&n, 4, 1, f) == 1 && fwrite(&raw_len, 8, 1, f) == 1 &&
              fwrite(&stored_len, 8, 1, f) == 1 &&
              fwrite(&crc, 4, 1, f) == 1 &&
              fwrite(stored.data(), 1, stored.size(), f) == stored.size();
    pending.clear();
    pending_bytes = 0;
    return ok;
  }
};

struct Scanner {
  FILE* f = nullptr;
  std::vector<std::string> records;  // current chunk
  size_t cursor = 0;

  // returns: 1 ok, 0 eof, -1 corrupt
  int LoadChunk() {
    uint32_t magic = 0, comp = 0, n = 0, crc = 0;
    uint64_t raw_len = 0, stored_len = 0;
    if (fread(&magic, 4, 1, f) != 1) return 0;  // clean EOF
    if (magic != kMagic || fread(&comp, 4, 1, f) != 1 ||
        fread(&n, 4, 1, f) != 1 || fread(&raw_len, 8, 1, f) != 1 ||
        fread(&stored_len, 8, 1, f) != 1 || fread(&crc, 4, 1, f) != 1) {
      return -1;
    }
    std::string stored(stored_len, '\0');
    if (stored_len &&
        fread(&stored[0], 1, stored_len, f) != stored_len) {
      return -1;
    }
    if (crc32(0L, reinterpret_cast<const Bytef*>(stored.data()),
              stored.size()) != crc) {
      return -1;
    }
    std::string raw;
    if (comp == kZlib) {
      raw.resize(raw_len);
      uLongf out_len = raw_len;
      if (uncompress(reinterpret_cast<Bytef*>(&raw[0]), &out_len,
                     reinterpret_cast<const Bytef*>(stored.data()),
                     stored.size()) != Z_OK ||
          out_len != raw_len) {
        return -1;
      }
    } else {
      raw = std::move(stored);
    }
    records.clear();
    cursor = 0;
    size_t pos = 0;
    for (uint32_t i = 0; i < n; ++i) {
      if (pos + 8 > raw.size()) return -1;
      uint64_t len;
      memcpy(&len, raw.data() + pos, 8);
      pos += 8;
      if (pos + len > raw.size()) return -1;
      records.emplace_back(raw.data() + pos, len);
      pos += len;
    }
    return 1;
  }
};

}  // namespace

extern "C" {

void* pt_recordio_writer_open(const char* path, int compressor,
                              long max_chunk_bytes) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  w->compressor = compressor ? kZlib : kNone;
  if (max_chunk_bytes > 0) w->max_chunk_bytes = max_chunk_bytes;
  return w;
}

int pt_recordio_write(void* wp, const char* data, long len) {
  auto* w = static_cast<Writer*>(wp);
  w->pending.emplace_back(data, len);
  w->pending_bytes += len;
  if (w->pending_bytes >= w->max_chunk_bytes) {
    return w->FlushChunk() ? 0 : -1;
  }
  return 0;
}

int pt_recordio_writer_close(void* wp) {
  auto* w = static_cast<Writer*>(wp);
  bool ok = w->FlushChunk();
  fclose(w->f);
  delete w;
  return ok ? 0 : -1;
}

void* pt_recordio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* s = new Scanner();
  s->f = f;
  return s;
}

// Returns record length (>=0) with *out malloc'd; -1 on EOF; -2 on corrupt.
long pt_recordio_next(void* sp, char** out) {
  auto* s = static_cast<Scanner*>(sp);
  if (s->cursor >= s->records.size()) {
    int r = s->LoadChunk();
    if (r == 0) return -1;
    if (r < 0) return -2;
  }
  const std::string& rec = s->records[s->cursor++];
  *out = static_cast<char*>(malloc(rec.size() ? rec.size() : 1));
  memcpy(*out, rec.data(), rec.size());
  return static_cast<long>(rec.size());
}

void pt_recordio_scanner_close(void* sp) {
  auto* s = static_cast<Scanner*>(sp);
  fclose(s->f);
  delete s;
}

void pt_free(char* p) { free(p); }

}  // extern "C"
