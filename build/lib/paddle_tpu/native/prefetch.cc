// Multi-file prefetching recordio reader (native component).
//
// ref: the reference's native reader stack — open_files + multi-file
// readers + double_buffer (paddle/fluid/operators/reader/, e.g.
// open_files_op.cc, create_double_buffer_reader_op.cc:22,
// buffered_reader): N C++ worker threads scan recordio shards and stage
// records into a bounded queue so the Python train loop never blocks on
// file IO or decompression.  Fresh TPU-era design over this repo's PTR1
// chunk format (recordio.cc), not a port.
//
// C API (ctypes-consumed; pybind11 absent from the image):
//   pt_prefetch_create(paths, n_paths, n_threads, capacity)
//   pt_prefetch_next(p, &out, timeout_s)
//       -> len | -1 end | -2 timeout | -3 shard error (unopenable/corrupt)
//   pt_prefetch_destroy(p)

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

// recordio.cc scanner entry points (same shared library).
extern "C" {
void* pt_recordio_scanner_open(const char* path);
long pt_recordio_next(void* sp, char** out);
void pt_recordio_scanner_close(void* sp);
void pt_free(char* p);
}

namespace {

struct Prefetcher {
  std::vector<std::string> paths;
  size_t capacity;
  std::deque<std::string> buf;
  std::mutex mu;
  std::condition_variable not_empty, not_full;
  std::vector<std::thread> workers;
  size_t n_workers = 0;  // fixed BEFORE any thread starts: workers.size()
                         // races with spawning and must not be the stride
  int live_workers = 0;
  bool stop = false;
  bool error = false;  // an unopenable or corrupt shard must surface, not
                       // silently truncate the dataset

  void worker(size_t start) {
    // files partitioned round-robin across threads
    for (size_t i = start; i < paths.size(); i += n_workers) {
      void* sc = pt_recordio_scanner_open(paths[i].c_str());
      if (sc == nullptr) {
        std::lock_guard<std::mutex> lk(mu);
        error = true;
        continue;
      }
      for (;;) {
        char* rec = nullptr;
        long n = pt_recordio_next(sc, &rec);
        if (n == -2) {  // corrupt chunk
          std::lock_guard<std::mutex> lk(mu);
          error = true;
          break;
        }
        if (n < 0) break;
        std::unique_lock<std::mutex> lk(mu);
        not_full.wait(lk, [&] { return buf.size() < capacity || stop; });
        if (stop) {
          pt_free(rec);
          pt_recordio_scanner_close(sc);
          goto done;
        }
        buf.emplace_back(rec, rec + n);
        pt_free(rec);
        not_empty.notify_one();
      }
      pt_recordio_scanner_close(sc);
    }
  done:
    std::lock_guard<std::mutex> lk(mu);
    if (--live_workers == 0) not_empty.notify_all();
  }
};

}  // namespace

extern "C" {

void* pt_prefetch_create(const char** paths, int n_paths, int n_threads,
                         long capacity) {
  auto* p = new Prefetcher();
  for (int i = 0; i < n_paths; ++i) p->paths.emplace_back(paths[i]);
  p->capacity = capacity > 0 ? static_cast<size_t>(capacity) : 256;
  int n = n_threads > 0 ? n_threads : 1;
  if (n > n_paths && n_paths > 0) n = n_paths;
  p->live_workers = n;
  p->n_workers = static_cast<size_t>(n);
  p->workers.reserve(n);
  for (int t = 0; t < n; ++t)
    p->workers.emplace_back([p, t] { p->worker(static_cast<size_t>(t)); });
  return p;
}

long pt_prefetch_next(void* pp, char** out, double timeout_s) {
  auto* p = static_cast<Prefetcher*>(pp);
  std::unique_lock<std::mutex> lk(p->mu);
  auto ready = [&] { return !p->buf.empty() || p->live_workers == 0; };
  if (timeout_s < 0) {
    p->not_empty.wait(lk, ready);
  } else if (!p->not_empty.wait_for(
                 lk, std::chrono::duration<double>(timeout_s), ready)) {
    return -2;  // timeout
  }
  if (p->buf.empty()) return p->error ? -3 : -1;  // drained (or failed)
  std::string rec = std::move(p->buf.front());
  p->buf.pop_front();
  p->not_full.notify_one();
  lk.unlock();
  *out = static_cast<char*>(malloc(rec.size()));
  memcpy(*out, rec.data(), rec.size());
  return static_cast<long>(rec.size());
}

void pt_prefetch_destroy(void* pp) {
  auto* p = static_cast<Prefetcher*>(pp);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
  }
  p->not_full.notify_all();
  p->not_empty.notify_all();
  for (auto& t : p->workers) t.join();
  delete p;
}

}  // extern "C"
