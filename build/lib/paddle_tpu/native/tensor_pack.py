"""Binary packing of tensor batches for the native byte queues / recordio.

ref: the reference serializes LoDTensors as version + proto + raw bytes
(framework/lod_tensor.cc SerializeToStream) for both recordio records and
pserver messages.  This is the TPU-era equivalent wire form used by
py_reader queues and recordio dataset files.

batch := u32 n_tensors | tensor*
tensor := u8 dtype_len | dtype_str | u8 ndim | i64 dims[ndim]
        | u8 lod_levels | { u32 count | i64 offsets[count] }*
        | raw bytes (C-order)
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np


def pack_batch(items: Sequence[Tuple[np.ndarray, Optional[tuple]]]) -> bytes:
    out = [struct.pack("<I", len(items))]
    for arr, lod in items:
        arr = np.ascontiguousarray(arr)
        dt = arr.dtype.str.encode()
        out.append(struct.pack("<B", len(dt)))
        out.append(dt)
        out.append(struct.pack("<B", arr.ndim))
        out.append(struct.pack(f"<{arr.ndim}q", *arr.shape))
        lod = lod or ()
        out.append(struct.pack("<B", len(lod)))
        for level in lod:
            out.append(struct.pack("<I", len(level)))
            out.append(struct.pack(f"<{len(level)}q", *level))
        out.append(arr.tobytes())
    return b"".join(out)


def unpack_batch(data: bytes) -> List[Tuple[np.ndarray, tuple]]:
    pos = 0
    (n,) = struct.unpack_from("<I", data, pos)
    pos += 4
    items = []
    for _ in range(n):
        (dt_len,) = struct.unpack_from("<B", data, pos)
        pos += 1
        dt = np.dtype(data[pos: pos + dt_len].decode())
        pos += dt_len
        (ndim,) = struct.unpack_from("<B", data, pos)
        pos += 1
        dims = struct.unpack_from(f"<{ndim}q", data, pos)
        pos += 8 * ndim
        (levels,) = struct.unpack_from("<B", data, pos)
        pos += 1
        lod = []
        for _ in range(levels):
            (cnt,) = struct.unpack_from("<I", data, pos)
            pos += 4
            lod.append(tuple(struct.unpack_from(f"<{cnt}q", data, pos)))
            pos += 8 * cnt
        nbytes = int(np.prod(dims)) * dt.itemsize if ndim else dt.itemsize
        arr = np.frombuffer(data, dtype=dt, count=int(np.prod(dims)) if ndim
                            else 1, offset=pos).reshape(dims)
        pos += nbytes
        items.append((arr, tuple(lod)))
    return items
