// Bounded blocking byte-buffer queue (native component).
//
// ref: paddle/fluid/operators/reader/lod_tensor_blocking_queue.h:31 and
// framework/channel.h — the host-side hand-off between Python reader
// threads and the device feed path (py_reader / double_buffer).  TPU-era
// design: payloads are opaque byte buffers (the Python side packs
// tensor batches), closing wakes all waiters, pops drain remaining items
// after close (the reference's kill/close semantics).

#include <chrono>
#include <condition_variable>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>

namespace {

struct Queue {
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  std::deque<std::string> items;
  size_t capacity;
  bool closed = false;

  explicit Queue(size_t cap) : capacity(cap ? cap : 1) {}
};

}  // namespace

extern "C" {

void* pt_queue_create(long capacity) {
  return new Queue(static_cast<size_t>(capacity));
}

// 0 ok; -1 closed; -2 timeout.  timeout<0 => wait forever.
int pt_queue_push(void* qp, const char* data, long len, double timeout_s) {
  auto* q = static_cast<Queue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  auto ready = [q] { return q->closed || q->items.size() < q->capacity; };
  if (timeout_s < 0) {
    q->not_full.wait(lk, ready);
  } else if (!q->not_full.wait_for(
                 lk, std::chrono::duration<double>(timeout_s), ready)) {
    return -2;
  }
  if (q->closed) return -1;
  q->items.emplace_back(data, len);
  q->not_empty.notify_one();
  return 0;
}

// >=0: length, *out malloc'd; -1 closed-and-drained; -2 timeout.
long pt_queue_pop(void* qp, char** out, double timeout_s) {
  auto* q = static_cast<Queue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  auto ready = [q] { return q->closed || !q->items.empty(); };
  if (timeout_s < 0) {
    q->not_empty.wait(lk, ready);
  } else if (!q->not_empty.wait_for(
                 lk, std::chrono::duration<double>(timeout_s), ready)) {
    return -2;
  }
  if (q->items.empty()) return -1;  // closed and drained
  std::string item = std::move(q->items.front());
  q->items.pop_front();
  q->not_full.notify_one();
  lk.unlock();
  *out = static_cast<char*>(malloc(item.size() ? item.size() : 1));
  memcpy(*out, item.data(), item.size());
  return static_cast<long>(item.size());
}

void pt_queue_close(void* qp) {
  auto* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  q->closed = true;
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

int pt_queue_is_closed(void* qp) {
  auto* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->closed ? 1 : 0;
}

long pt_queue_size(void* qp) {
  auto* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  return static_cast<long>(q->items.size());
}

void pt_queue_reopen(void* qp) {
  auto* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  q->closed = false;
  q->items.clear();
}

void pt_queue_destroy(void* qp) { delete static_cast<Queue*>(qp); }

}  // extern "C"
