"""Native runtime components (C++ via ctypes).

ref: the reference's native recordio (paddle/fluid/recordio/) and reader
blocking queue (operators/reader/lod_tensor_blocking_queue.h:31).  The
shared library is built lazily with g++ on first use and cached next to
the sources; if no toolchain is available the pure-Python fallbacks keep
the API working (slower, same semantics).
"""

from __future__ import annotations

import ctypes
import os
import queue as _pyqueue
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libpaddle_tpu_native.so")
_SRC = [os.path.join(_HERE, "recordio.cc"),
        os.path.join(_HERE, "blocking_queue.cc"),
        os.path.join(_HERE, "prefetch.cc")]

_lib = None
_lib_lock = threading.Lock()


def _build() -> bool:
    try:
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               *_SRC, "-o", _SO, "-lz", "-lpthread"]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib():
    """The loaded native library, or None (fallbacks used)."""
    global _lib
    if _lib is not None:
        return _lib or None
    with _lib_lock:
        if _lib is not None:
            return _lib or None
        need_build = not os.path.exists(_SO) or any(
            os.path.getmtime(s) > os.path.getmtime(_SO) for s in _SRC)
        if need_build and not _build():
            _lib = False
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _lib = False
            return None
        lib.pt_recordio_writer_open.restype = ctypes.c_void_p
        lib.pt_recordio_writer_open.argtypes = [ctypes.c_char_p,
                                                ctypes.c_int, ctypes.c_long]
        lib.pt_recordio_write.restype = ctypes.c_int
        lib.pt_recordio_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_long]
        lib.pt_recordio_writer_close.restype = ctypes.c_int
        lib.pt_recordio_writer_close.argtypes = [ctypes.c_void_p]
        lib.pt_recordio_scanner_open.restype = ctypes.c_void_p
        lib.pt_recordio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.pt_recordio_next.restype = ctypes.c_long
        lib.pt_recordio_next.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_char_p)]
        lib.pt_recordio_scanner_close.argtypes = [ctypes.c_void_p]
        lib.pt_free.argtypes = [ctypes.c_char_p]
        lib.pt_queue_create.restype = ctypes.c_void_p
        lib.pt_queue_create.argtypes = [ctypes.c_long]
        lib.pt_queue_push.restype = ctypes.c_int
        lib.pt_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_long, ctypes.c_double]
        lib.pt_queue_pop.restype = ctypes.c_long
        lib.pt_queue_pop.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_char_p),
                                     ctypes.c_double]
        for name in ("pt_queue_close", "pt_queue_destroy",
                     "pt_queue_reopen"):
            getattr(lib, name).argtypes = [ctypes.c_void_p]
        lib.pt_queue_is_closed.restype = ctypes.c_int
        lib.pt_queue_is_closed.argtypes = [ctypes.c_void_p]
        lib.pt_queue_size.restype = ctypes.c_long
        lib.pt_queue_size.argtypes = [ctypes.c_void_p]
        lib.pt_prefetch_create.restype = ctypes.c_void_p
        lib.pt_prefetch_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
            ctypes.c_long]
        lib.pt_prefetch_next.restype = ctypes.c_long
        lib.pt_prefetch_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_double]
        lib.pt_prefetch_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def native_available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# RecordIO
# ---------------------------------------------------------------------------


class RecordIOWriter:
    """ref: recordio/writer.h + python recordio_writer.py surface."""

    def __init__(self, path: str, compressor: int = 1,
                 max_chunk_bytes: int = 1 << 20):
        self._lib = get_lib()
        self._path = path
        if self._lib:
            self._h = self._lib.pt_recordio_writer_open(
                path.encode(), int(bool(compressor)), max_chunk_bytes)
            if not self._h:
                raise IOError(f"cannot open {path} for writing")
        else:
            import zlib

            self._zlib = zlib
            self._f = open(path, "wb")
            self._compressor = int(bool(compressor))
            self._pending = []
            self._pending_bytes = 0
            self._max = max_chunk_bytes

    def write(self, record: bytes):
        if isinstance(record, str):
            record = record.encode()
        if self._lib:
            if self._lib.pt_recordio_write(self._h, record,
                                           len(record)) != 0:
                raise IOError("recordio write failed")
            return
        self._pending.append(bytes(record))
        self._pending_bytes += len(record)
        if self._pending_bytes >= self._max:
            self._flush_py()

    def _flush_py(self):
        import struct

        if not self._pending:
            return
        raw = b"".join(struct.pack("<Q", len(r)) + r for r in self._pending)
        stored = self._zlib.compress(raw, 1) if self._compressor else raw
        crc = self._zlib.crc32(stored) & 0xFFFFFFFF
        self._f.write(struct.pack("<IIIQQI", 0x50545231, self._compressor,
                                  len(self._pending), len(raw), len(stored),
                                  crc))
        self._f.write(stored)
        self._pending, self._pending_bytes = [], 0

    def close(self):
        if self._lib:
            if self._lib.pt_recordio_writer_close(self._h) != 0:
                raise IOError("recordio close failed")
            self._h = None
        else:
            self._flush_py()
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
        return False


class RecordIOScanner:
    """ref: recordio/scanner.h — iterate records of a file."""

    def __init__(self, path: str):
        self._lib = get_lib()
        self._path = path
        if self._lib:
            self._h = self._lib.pt_recordio_scanner_open(path.encode())
            if not self._h:
                raise IOError(f"cannot open {path}")
        else:
            self._f = open(path, "rb")
            self._chunk = []
            self._cursor = 0

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        if self._lib:
            out = ctypes.c_char_p()
            n = self._lib.pt_recordio_next(self._h, ctypes.byref(out))
            if n == -1:
                raise StopIteration
            if n == -2:
                raise IOError(f"corrupt recordio file {self._path}")
            data = ctypes.string_at(out, n)
            self._lib.pt_free(out)
            return data
        return self._next_py()

    def _next_py(self) -> bytes:
        import struct
        import zlib

        if self._cursor >= len(self._chunk):
            head = self._f.read(32)
            if not head:
                raise StopIteration
            if len(head) < 32:
                raise IOError("corrupt recordio header")
            magic, comp, n, raw_len, stored_len, crc = struct.unpack(
                "<IIIQQI", head)
            if magic != 0x50545231:
                raise IOError("bad recordio magic")
            stored = self._f.read(stored_len)
            if (zlib.crc32(stored) & 0xFFFFFFFF) != crc:
                raise IOError("recordio crc mismatch")
            raw = zlib.decompress(stored) if comp else stored
            self._chunk, self._cursor, pos = [], 0, 0
            for _ in range(n):
                (ln,) = struct.unpack_from("<Q", raw, pos)
                pos += 8
                self._chunk.append(raw[pos: pos + ln])
                pos += ln
        rec = self._chunk[self._cursor]
        self._cursor += 1
        return rec

    def close(self):
        if self._lib:
            if self._h:
                self._lib.pt_recordio_scanner_close(self._h)
                self._h = None
        else:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
        return False


# ---------------------------------------------------------------------------
# Blocking queue
# ---------------------------------------------------------------------------


class BlockingQueue:
    """Bounded byte-payload queue (ref: LoDTensorBlockingQueue)."""

    def __init__(self, capacity: int):
        self._lib = get_lib()
        self.capacity = capacity
        if self._lib:
            self._h = self._lib.pt_queue_create(capacity)
        else:
            self._q = _pyqueue.Queue(maxsize=capacity)
            self._closed = False

    def push(self, data: bytes, timeout: float = -1.0) -> bool:
        """False iff the queue is closed."""
        if self._lib:
            r = self._lib.pt_queue_push(self._h, data, len(data), timeout)
            if r == -2:
                raise TimeoutError("queue push timed out")
            return r == 0
        # poll so close() wakes blocked producers (the C++ path uses
        # condvar notification)
        import time as _time

        deadline = None if timeout < 0 else _time.monotonic() + timeout
        while True:
            if self._closed:
                return False
            try:
                self._q.put(data, timeout=0.05)
                return True
            except _pyqueue.Full:
                if deadline is not None and _time.monotonic() > deadline:
                    raise TimeoutError("queue push timed out") from None

    def pop(self, timeout: float = -1.0):
        """bytes, or None when closed and drained."""
        if self._lib:
            out = ctypes.c_char_p()
            n = self._lib.pt_queue_pop(self._h, ctypes.byref(out), timeout)
            if n == -1:
                return None
            if n == -2:
                raise TimeoutError("queue pop timed out")
            data = ctypes.string_at(out, n)
            self._lib.pt_free(out)
            return data
        while True:
            try:
                return self._q.get(timeout=0.05 if timeout < 0 else timeout)
            except _pyqueue.Empty:
                if self._closed:
                    return None
                if timeout >= 0:
                    raise TimeoutError("queue pop timed out") from None

    def close(self):
        if self._lib:
            self._lib.pt_queue_close(self._h)
        else:
            self._closed = True

    def reopen(self):
        if self._lib:
            self._lib.pt_queue_reopen(self._h)
        else:
            self._q = _pyqueue.Queue(maxsize=self.capacity)
            self._closed = False

    def is_closed(self) -> bool:
        if self._lib:
            return bool(self._lib.pt_queue_is_closed(self._h))
        return self._closed

    def size(self) -> int:
        if self._lib:
            return self._lib.pt_queue_size(self._h)
        return self._q.qsize()

    def __del__(self):
        try:
            if self._lib and self._h:
                self._lib.pt_queue_destroy(self._h)
                self._h = None
        except Exception:
            pass


class PrefetchReader:
    """Multi-threaded prefetching reader over recordio shards (ref: the
    reference's open_files + double_buffer native reader stack,
    operators/reader/open_files_op.cc, create_double_buffer_reader_op.cc).
    N C++ threads scan the files and stage records in a bounded buffer;
    iteration yields raw record bytes.  An unopenable or corrupt shard
    raises IOError (after already-buffered records drain) rather than
    silently truncating the dataset.  Pure-Python thread fallback (over
    the module's BlockingQueue) when no native toolchain is available."""

    def __init__(self, paths, n_threads: int = 2, capacity: int = 256):
        self._paths = [os.fspath(p) for p in paths]
        self._lib = get_lib()
        self._h = None
        self._done = False
        if self._lib is not None:
            arr = (ctypes.c_char_p * len(self._paths))(
                *[p.encode() for p in self._paths])
            self._h = ctypes.c_void_p(self._lib.pt_prefetch_create(
                arr, len(self._paths), int(n_threads), int(capacity)))
            return
        # fallback: worker threads over the (pure-Python) BlockingQueue;
        # q.push returning False after close() stops abandoned workers
        self._q = BlockingQueue(capacity)
        self._errors: list = []
        n = max(1, min(int(n_threads), len(self._paths) or 1))
        self._live_left = n
        self._live_lock = threading.Lock()

        def work(start):
            try:
                for i in range(start, len(self._paths), n):
                    for rec in RecordIOScanner(self._paths[i]):
                        if not self._q.push(rec):
                            return  # reader closed early
            except Exception as exc:  # surfaced to the consumer
                self._errors.append(exc)
            finally:
                with self._live_lock:
                    self._live_left -= 1
                    if self._live_left == 0:
                        self._q.close()

        for t in range(n):
            threading.Thread(target=work, args=(t,), daemon=True).start()

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        if self._done:
            raise StopIteration
        if self._lib is not None:
            out = ctypes.c_char_p()
            n = self._lib.pt_prefetch_next(
                self._h, ctypes.byref(out), ctypes.c_double(-1.0))
            if n == -3:
                self.close()
                raise IOError(
                    "PrefetchReader: a shard was unreadable or corrupt")
            if n < 0:
                self.close()
                raise StopIteration
            data = ctypes.string_at(out, n)
            self._lib.pt_free(out)
            return data
        rec = self._q.pop()
        if rec is None:  # closed + drained
            self._done = True
            if self._errors:
                raise IOError(
                    f"PrefetchReader: shard failed: {self._errors[0]!r}")
            raise StopIteration
        return rec

    def close(self):
        self._done = True
        if self._h is not None:
            self._lib.pt_prefetch_destroy(self._h)
            self._h = None
        elif self._lib is None and hasattr(self, "_q"):
            self._q.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass