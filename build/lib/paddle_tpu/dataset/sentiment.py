"""NLTK movie-reviews sentiment reader (ref:
python/paddle/dataset/sentiment.py — train/test yield (word-id list,
0/1 label); get_word_dict :64).

Synthetic fallback: two word distributions (positive ids low, negative ids
high, with overlap) — linearly separable, like the real set."""

from __future__ import annotations

import numpy as np

VOCAB = 400
N_TRAIN = 800
N_TEST = 200


def get_word_dict():
    return {f"w{i}": i for i in range(VOCAB)}


def _samples(n, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        label = int(rng.randint(2))
        ln = int(rng.randint(8, 30))
        center = VOCAB // 4 if label else 3 * VOCAB // 4
        ids = np.clip(rng.normal(center, VOCAB // 6, size=ln), 0,
                      VOCAB - 1).astype(np.int64)
        yield list(ids), label


def train():
    def reader():
        yield from _samples(N_TRAIN, 61)

    return reader


def test():
    def reader():
        yield from _samples(N_TEST, 62)

    return reader
