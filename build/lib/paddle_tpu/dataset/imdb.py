"""IMDB sentiment reader (ref: python/paddle/dataset/imdb.py);
synthetic fallback: integer token sequences with class-correlated tokens."""

from __future__ import annotations

import numpy as np

VOCAB_SIZE = 5147
TRAIN_SIZE = 2048
TEST_SIZE = 256


def word_dict():
    return {i: i for i in range(VOCAB_SIZE)}


def _make(n, seed):
    rng = np.random.RandomState(seed)
    samples = []
    for _ in range(n):
        label = int(rng.randint(0, 2))
        length = int(rng.randint(8, 120))
        base = rng.randint(0, VOCAB_SIZE // 2, size=length)
        if label:
            base = base + VOCAB_SIZE // 2  # positive-class tokens
        samples.append((base.astype(np.int64).tolist(), label))
    return samples


def train(word_idx=None):
    data = _make(TRAIN_SIZE, 90351)

    def reader():
        for seq, label in data:
            yield seq, label

    return reader


def test(word_idx=None):
    data = _make(TEST_SIZE, 90352)

    def reader():
        for seq, label in data:
            yield seq, label

    return reader
