"""Datasets (ref: python/paddle/dataset/ — mnist, cifar, uci_housing, ...).

The reference auto-downloads into ~/.cache/paddle.  This environment has no
network egress, so each dataset falls back to a deterministic synthetic
generator with the real shapes/dtypes/cardinalities when the cached copy is
absent — enough for the train-loop, checkpoint, and benchmark harnesses.
"""

from . import (cifar, common, conll05, flowers, imdb, imikolov, mnist,
               movielens, mq2007, sentiment, uci_housing, voc2012, wmt14,
               wmt16)

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "imikolov", "movielens",
           "wmt14", "wmt16", "flowers", "conll05", "sentiment", "voc2012", "mq2007",
           "common"]
