"""WMT16 en-de reader (ref: python/paddle/dataset/wmt16.py — train/test
yield (src_ids, trg_ids, trg_ids_next) with <s>/<e>/<unk> at ids 0/1/2,
get_dict :318).

Synthetic fallback: a deterministic "translation" (target token = permuted
source token, reversed order) so seq2seq models can genuinely learn the
mapping — shapes and id conventions identical to the real set."""

from __future__ import annotations

import os

import numpy as np

from . import common

# same special-token convention as the reference loader
START_MARK = "<s>"
END_MARK = "<e>"
UNK_MARK = "<unk>"
START_ID, END_ID, UNK_ID = 0, 1, 2

N_TRAIN = 2000
N_TEST = 200


def _synthetic_pairs(n, src_dict_size, trg_dict_size, seed):
    rng = np.random.RandomState(seed)
    v_src = max(src_dict_size - 3, 5)
    v_trg = max(trg_dict_size - 3, 5)
    # the "translation rule" (the permutation) comes from a FIXED seed so
    # train/test/validation teach and test the SAME mapping — only the
    # sampled sentences differ per split, as with a real corpus
    perm = np.random.RandomState(1604).permutation(max(v_src, v_trg))
    for _ in range(n):
        ln = int(rng.randint(3, 12))
        src = rng.randint(0, v_src, size=ln)
        trg = [int(perm[w] % v_trg) for w in reversed(src)]
        src_ids = [START_ID] + [int(w) + 3 for w in src] + [END_ID]
        trg_ids = [START_ID] + [int(w) + 3 for w in trg]
        trg_next = trg_ids[1:] + [END_ID]
        yield src_ids, trg_ids, trg_next


def get_dict(lang, dict_size, reverse=False):
    """id<->word table with the 3 specials first (ref :318)."""
    words = [START_MARK, END_MARK, UNK_MARK] + \
        [f"{lang}{i}" for i in range(dict_size - 3)]
    if reverse:
        return {i: w for i, w in enumerate(words)}
    return {w: i for i, w in enumerate(words)}


def train(src_dict_size, trg_dict_size, src_lang="en"):
    def reader():
        yield from _synthetic_pairs(N_TRAIN, src_dict_size, trg_dict_size, 31)

    return reader


def test(src_dict_size, trg_dict_size, src_lang="en"):
    def reader():
        yield from _synthetic_pairs(N_TEST, src_dict_size, trg_dict_size, 32)

    return reader


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    def reader():
        yield from _synthetic_pairs(N_TEST, src_dict_size, trg_dict_size, 33)

    return reader
