"""PASCAL VOC2012 segmentation reader (ref:
python/paddle/dataset/voc2012.py — train/test/val yield (image CHW float,
label mask HW int32)).

Synthetic fallback: images containing a colored rectangle whose mask is the
label — segmentation models can fit it."""

from __future__ import annotations

import numpy as np

N_CLASSES = 21
SHAPE = (3, 48, 48)
N_TRAIN = 200
N_TEST = 50


def _samples(n, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        img = rng.normal(0, 0.1, size=SHAPE).astype(np.float32)
        mask = np.zeros(SHAPE[1:], np.int32)
        cls = int(rng.randint(1, N_CLASSES))
        y0, x0 = rng.randint(4, 20, size=2)
        h, w = rng.randint(8, 24, size=2)
        mask[y0:y0 + h, x0:x0 + w] = cls
        img[:, y0:y0 + h, x0:x0 + w] += cls / N_CLASSES
        yield img, mask


def train():
    def reader():
        yield from _samples(N_TRAIN, 71)

    return reader


def test():
    def reader():
        yield from _samples(N_TEST, 72)

    return reader


def val():
    def reader():
        yield from _samples(N_TEST, 73)

    return reader
