"""Dataset cache helpers (ref: python/paddle/dataset/common.py)."""

from __future__ import annotations

import os

DATA_HOME = os.path.expanduser("~/.cache/paddle_tpu/dataset")


def cached_path(*parts):
    return os.path.join(DATA_HOME, *parts)


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)
    return path
