"""WMT14 en-fr reader (ref: python/paddle/dataset/wmt14.py — train/test
yield (src_ids, trg_ids, trg_ids_next); get_dict returns (src, trg) word
dicts; same <s>/<e>/<unk> = 0/1/2 convention as wmt16).

Synthetic fallback identical in shape/contract to the real set (zero-egress
environment); the deterministic permuted-reverse "translation" is learnable
by seq2seq models."""

from __future__ import annotations

from . import wmt16 as _w16

START_ID, END_ID, UNK_ID = _w16.START_ID, _w16.END_ID, _w16.UNK_ID


def train(dict_size):
    def reader():
        yield from _w16._synthetic_pairs(_w16.N_TRAIN, dict_size, dict_size,
                                         41)

    return reader


def test(dict_size):
    def reader():
        yield from _w16._synthetic_pairs(_w16.N_TEST, dict_size, dict_size,
                                         42)

    return reader


def get_dict(dict_size, reverse=False):
    """(src_dict, trg_dict) pair (ref wmt14.py get_dict)."""
    return (_w16.get_dict("en", dict_size, reverse),
            _w16.get_dict("fr", dict_size, reverse))
