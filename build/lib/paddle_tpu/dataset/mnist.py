"""MNIST reader (ref: python/paddle/dataset/mnist.py).

Real MNIST if cached locally; otherwise a deterministic synthetic set with
identical shapes ([784] float32 in [-1, 1], int64 label in [0, 10))."""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from . import common

TRAIN_SIZE = 8192
TEST_SIZE = 1024


def _synthetic(n, seed):
    # class means come from a FIXED seed shared by both splits — a model
    # trained on train() must generalize to test() exactly as with the
    # real dataset; only labels/noise vary per split
    means = np.random.RandomState(4117).uniform(
        -0.5, 0.5, size=(10, 784)).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(np.int64)
    imgs = means[labels] + rng.normal(0, 0.3, size=(n, 784)).astype(np.float32)
    imgs = np.clip(imgs, -1.0, 1.0).astype(np.float32)
    return imgs, labels


def _reader_from_arrays(imgs, labels):
    def reader():
        for i in range(len(labels)):
            yield imgs[i], int(labels[i])

    return reader


def _load_idx(image_path, label_path):
    with gzip.open(image_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        imgs = np.frombuffer(f.read(), np.uint8).reshape(n, rows * cols)
        imgs = imgs.astype(np.float32) / 127.5 - 1.0
    with gzip.open(label_path, "rb") as f:
        struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
    return imgs, labels


def _maybe_real(split):
    d = common.cached_path("mnist")
    image = os.path.join(d, f"{split}-images-idx3-ubyte.gz")
    label = os.path.join(d, f"{split}-labels-idx1-ubyte.gz")
    if os.path.exists(image) and os.path.exists(label):
        return _load_idx(image, label)
    return None


def train():
    real = _maybe_real("train")
    if real is not None:
        return _reader_from_arrays(*real)
    return _reader_from_arrays(*_synthetic(TRAIN_SIZE, seed=90051))


def test():
    real = _maybe_real("t10k")
    if real is not None:
        return _reader_from_arrays(*real)
    return _reader_from_arrays(*_synthetic(TEST_SIZE, seed=90052))
