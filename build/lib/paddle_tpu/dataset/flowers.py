"""Oxford-102 flowers reader (ref: python/paddle/dataset/flowers.py —
train/test/valid yield (flattened 3x224x224 float image, int label)).

Synthetic fallback: class-conditioned color blobs, deterministic, so image
classifiers overfit the same way the real set allows."""

from __future__ import annotations

import numpy as np

N_CLASSES = 102
N_TRAIN = 512
N_TEST = 128
SHAPE = (3, 64, 64)  # reduced spatial size; same layout/contract


def _rows(n, seed):
    rng = np.random.RandomState(seed)
    means = rng.uniform(-0.6, 0.6, size=(N_CLASSES, 3)).astype(np.float32)
    for _ in range(n):
        label = int(rng.randint(N_CLASSES))
        img = means[label][:, None, None] + \
            rng.normal(0, 0.25, size=SHAPE).astype(np.float32)
        yield np.clip(img, -1, 1).astype(np.float32).flatten(), label


def train(mapper=None, buffered_size=1024, use_xmap=False):
    def reader():
        yield from _rows(N_TRAIN, 21)

    return reader


def test(mapper=None, buffered_size=1024, use_xmap=False):
    def reader():
        yield from _rows(N_TEST, 22)

    return reader


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    def reader():
        yield from _rows(N_TEST, 23)

    return reader
