"""LETOR MQ2007 learning-to-rank reader (ref:
python/paddle/dataset/mq2007.py — train/test with format
"pointwise" (feature, score), "pairwise" (d_hi, d_lo), or "listwise"
(query's doc features + scores)).

Synthetic fallback: relevance is a fixed linear function of the 46
features plus noise, so rankers recover it."""

from __future__ import annotations

import numpy as np

N_FEATURES = 46
N_QUERIES = 120
DOCS_PER_QUERY = 8

_W = np.random.RandomState(99).normal(size=(N_FEATURES,)).astype(np.float32)


def _queries(seed):
    rng = np.random.RandomState(seed)
    for _ in range(N_QUERIES):
        feats = rng.normal(size=(DOCS_PER_QUERY, N_FEATURES)) \
            .astype(np.float32)
        raw = feats @ _W + rng.normal(0, 0.1, size=DOCS_PER_QUERY)
        # LETOR grades 0..2
        score = np.digitize(raw, np.quantile(raw, [0.5, 0.85]))
        yield feats, score.astype(np.float32)


def _reader(seed, format):
    def pointwise():
        for feats, score in _queries(seed):
            for f, s in zip(feats, score):
                yield f, float(s)

    def pairwise():
        for feats, score in _queries(seed):
            for i in range(len(score)):
                for j in range(len(score)):
                    if score[i] > score[j]:
                        yield feats[i], feats[j]

    def listwise():
        for feats, score in _queries(seed):
            yield feats, score

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def train(format="pairwise"):
    return _reader(81, format)


def test(format="pairwise"):
    return _reader(82, format)
