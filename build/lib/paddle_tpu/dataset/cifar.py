"""CIFAR reader (ref: python/paddle/dataset/cifar.py); synthetic fallback."""

from __future__ import annotations

import numpy as np

TRAIN_SIZE = 4096
TEST_SIZE = 512


def _synthetic(n, classes, seed):
    rng = np.random.RandomState(seed)
    # class means from a FIXED seed so train/test share one distribution
    # (only labels/noise vary per split), like the real dataset
    means = np.random.RandomState(3217).uniform(
        0.2, 0.8, size=(classes, 3, 1, 1)).astype(np.float32)
    labels = rng.randint(0, classes, size=n).astype(np.int64)
    imgs = np.clip(means[labels] +
                   rng.normal(0, 0.2, size=(n, 3, 32, 32)).astype(np.float32),
                   0.0, 1.0)
    return imgs.reshape(n, 3 * 32 * 32), labels


def _reader(imgs, labels):
    def r():
        for i in range(len(labels)):
            yield imgs[i], int(labels[i])

    return r


def train10():
    return _reader(*_synthetic(TRAIN_SIZE, 10, 90151))


def test10():
    return _reader(*_synthetic(TEST_SIZE, 10, 90152))


def train100():
    return _reader(*_synthetic(TRAIN_SIZE, 100, 90153))


def test100():
    return _reader(*_synthetic(TEST_SIZE, 100, 90154))
