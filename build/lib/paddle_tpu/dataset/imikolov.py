"""imikolov (PTB) n-gram / sequence reader (ref:
python/paddle/dataset/imikolov.py — build_dict :64, train/test yield n-gram
id tuples :116 or seq pairs, DataType.NGRAM/SEQ).

Real PTB if cached under ~/.cache/paddle_tpu/dataset/imikolov/{train,valid}
.txt; otherwise a deterministic synthetic corpus with a learnable bigram
structure (each word strongly predicts its successor) so word2vec-style
models converge like they do on the real set."""

from __future__ import annotations

import os

import numpy as np

from . import common

VOCAB = 200
N_TRAIN_SENT = 2000
N_TEST_SENT = 200


class DataType:
    NGRAM = 1
    SEQ = 2


def _synthetic_corpus(n_sentences, seed):
    rng = np.random.RandomState(seed)
    # markov chain with a dominant successor per word -> learnable; the
    # successor table uses a FIXED seed so train/test share the language
    # model being learned (only the sampled sentences differ per split)
    succ = np.random.RandomState(2304).permutation(VOCAB)
    sents = []
    for _ in range(n_sentences):
        w = int(rng.randint(VOCAB))
        sent = [w]
        for _ in range(int(rng.randint(5, 15))):
            w = int(succ[w]) if rng.uniform() < 0.8 else int(rng.randint(VOCAB))
            sent.append(w)
        sents.append(["w%d" % w for w in sent])
    return sents


def _real_corpus(split):
    path = common.cached_path("imikolov", f"{split}.txt")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return [line.strip().split() for line in f if line.strip()]


def _corpus(split):
    real = _real_corpus(split)
    if real is not None:
        return real
    if split == "train":
        return _synthetic_corpus(N_TRAIN_SENT, 91)
    return _synthetic_corpus(N_TEST_SENT, 92)


def build_dict(min_word_freq=1):
    """word -> id; '<unk>' maps every OOV (ref :64 keeps '<s>'/'<e>' out)."""
    freq = {}
    for sent in _corpus("train"):
        for w in sent:
            freq[w] = freq.get(w, 0) + 1
    words = sorted([w for w, c in freq.items() if c >= min_word_freq],
                   key=lambda w: (-freq[w], w))
    word_idx = {w: i for i, w in enumerate(words)}
    word_idx["<unk>"] = len(words)
    return word_idx


def _reader(split, word_idx, n, data_type):
    unk = word_idx["<unk>"]

    def reader():
        for sent in _corpus(split):
            ids = [word_idx.get("<s>", unk)] + \
                [word_idx.get(w, unk) for w in sent] + \
                [word_idx.get("<e>", unk)]
            if data_type == DataType.NGRAM:
                if len(ids) >= n:
                    for i in range(n, len(ids) + 1):
                        yield tuple(ids[i - n: i])
            else:
                yield ids[:-1], ids[1:]

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _reader("train", word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _reader("test", word_idx, n, data_type)
