"""UCI housing reader (ref: python/paddle/dataset/uci_housing.py);
synthetic linear-regression fallback with the real 13-feature shape."""

from __future__ import annotations

import numpy as np

TRAIN_SIZE = 404
TEST_SIZE = 102

_rng = np.random.RandomState(90251)
_TRUE_W = _rng.uniform(-1, 1, size=13).astype(np.float32)


def _make(n, seed):
    rng = np.random.RandomState(seed)
    x = rng.normal(0, 1, size=(n, 13)).astype(np.float32)
    y = (x @ _TRUE_W + 0.1 * rng.normal(0, 1, size=n)).astype(np.float32)
    return x, y.reshape(-1, 1)


def train():
    x, y = _make(TRAIN_SIZE, 90252)

    def reader():
        for i in range(len(x)):
            yield x[i], y[i]

    return reader


def test():
    x, y = _make(TEST_SIZE, 90253)

    def reader():
        for i in range(len(x)):
            yield x[i], y[i]

    return reader
